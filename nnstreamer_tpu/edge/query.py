"""tensor_query — offload a pipeline stage to a server pipeline.

Parity targets (/root/reference/gst/nnstreamer/tensor_query/):
- ``tensor_query_client`` — sink chain serializes the buffer, sends it to
  the server, blocks on an answer queue with a timeout, and pushes the
  answer on its src pad; outstanding requests beyond ``max-request`` drop
  the input instead of queueing unboundedly (tensor_query_client.c:673-741).
- ``tensor_query_serversrc`` — accepts client connections, stamps each
  incoming query with ``client_id`` meta, and pushes it into the server
  pipeline (tensor_query_serversrc.c:483, tensor_meta.c:23).
- ``tensor_query_serversink`` — reads the ``client_id`` meta off the
  processed buffer and sends it back to exactly that client; metaless
  frames are dropped, and a run of them errors the pipeline
  (tensor_query_serversink.c:290).
- the query-server registry pairing src/sink by ``id`` and holding the
  server's caps for client negotiation (tensor_query_server.c).

TPU-native notes: with ``connect-type=inproc`` the round-trip is a queue
hop carrying device-resident buffers (HBM never drained); ``tcp`` uses the
MetaInfo-headed wire codec for true cross-host offload.  For *intra-pod*
scale-out prefer sharding one jitted computation over the mesh
(parallel/sharded.py) — these elements are the cross-process/cross-host
axis, mirroring the reference's "among-device AI".
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from collections import OrderedDict
from typing import Dict, Optional

from ..chaos import hooks as _chaos
from ..chaos.plan import apply_wire_op
from ..chaos.retrypolicy import RetryPolicy
from ..core import Buffer, Caps, TensorFormat, TensorsSpec
from ..obs import hooks as _hooks
from ..obs import tracectx
from ..obs.metrics import LinkMetrics
from ..obs.tracer import TRACE_META_KEY
from ..runtime.element import (
    Element,
    NegotiationError,
    Pad,
    SinkElement,
    SourceElement,
    StreamError,
)
from ..runtime.registry import register_element
from ..utils.log import loge, logw
from .ntputil import PeerClock, async_ntp_epoch_fn
from .transport import Envelope, connect, make_server
from .wire import MSG_PUBLISH, MSG_QUERY, MSG_REPLY, MSG_SUBSCRIBE


def _parse_ntp_servers(spec: str):
    """``host[:port],host[:port]`` → [(host, port)] (port 123 default)."""
    out = []
    for tok in str(spec or "").split(","):
        tok = tok.strip()
        if not tok:
            continue
        h, _, p = tok.rpartition(":")
        out.append((h or tok, int(p) if p.isdigit() else 123))
    return out


# -- query server registry ----------------------------------------------------


class _QueryServerEntry:
    """Shared state of one query server ``id``: the transport (owned by
    serversrc) and the sink-side caps registered for client negotiation."""

    def __init__(self):
        self.transport = None
        self.sink_caps: str = ""


_REG_LOCK = threading.Lock()
_SERVERS: Dict[int, _QueryServerEntry] = {}


def query_server_entry(server_id: int) -> _QueryServerEntry:
    with _REG_LOCK:
        if server_id not in _SERVERS:
            _SERVERS[server_id] = _QueryServerEntry()
        return _SERVERS[server_id]


# -- client -------------------------------------------------------------------


@register_element("tensor_query_client")
class TensorQueryClient(Element):
    """Acts like a remote tensor_filter: every buffer round-trips through
    the server pipeline.

    The hot path is PIPELINED (parity: the reference's async answer queue,
    tensor_query_client.c:673-741 — the edge thread keeps receiving while
    the sink chain blocks on ``g_async_queue_timeout_pop``): ``chain``
    sends without waiting, up to ``max_request`` requests ride the link
    concurrently, and a reader thread completes them as replies arrive —
    matched by ``seq``, out-of-order safe, pushed downstream in stream
    order.  On a high-RTT transport throughput is therefore bounded by
    bandwidth and server speed, not by requests × RTT (round-2 verdict
    item #4: the old send-then-recv chain capped throughput at 1/RTT).
    A request that outlives ``timeout`` is dropped so one lost reply
    cannot head-of-line-block the stream; a dead connection fails over
    mid-stream to ``alternate_hosts`` and resends what was in flight.

    Matching is exact when the server echoes ``query_seq`` meta (our
    serversrc always does).  If the server pipeline strips it (replies
    carry seq 0), pairing degrades to arrival order — the reference's
    semantics — with ordering tombstones so an expired request's late
    reply is absorbed in place rather than shifting later answers.  A
    server that silently DROPS queries in this mode skews FIFO pairing
    irreparably (no client can distinguish the dropped request's
    successor reply from its own); the client keeps the stream live,
    surfaces the drops as timeouts, and logs a loud diagnostic.
    """

    FACTORY = "tensor_query_client"

    def __init__(self, name=None, host: str = "localhost", port: int = 0,
                 dest_host: str = "", dest_port: int = 0,
                 connect_type: str = "tcp", timeout: int = 10000,
                 max_request: int = 8, caps=None, silent: bool = True,
                 alternate_hosts: str = "", topic: str = "",
                 trace: bool = True, ntp_servers: str = "",
                 device_channel: bool = True,
                 chaos: str = "", **props):
        self.host = host
        self.port = port
        self.dest_host = dest_host      # server address (falls back to host)
        self.dest_port = dest_port
        self.connect_type = connect_type
        # hybrid: host:port is the BROKER; topic names the server whose
        # TCP data address is discovered through it (reference
        # tensor_query/README.md:74-99)
        self.topic = topic
        self.timeout = timeout          # ms, parity: client timeout prop
        self.max_request = max_request
        self.caps = caps                # explicit out-caps override
        self.silent = silent
        # failover list "host:port,host:port" tried in order when the
        # primary is unreachable (parity: MQTT-hybrid reconnect to
        # alternate servers, reference tensor_query/README.md:74-99)
        self.alternate_hosts = alternate_hosts
        # distributed tracing: propagate a sampled buffer's trace
        # context to the server and absorb its spans from the reply
        # (Documentation/observability.md, "Distributed tracing")
        self.trace = trace
        # optional SNTP servers "host[:port],..." — a wall-clock
        # cross-check for span alignment; the query link itself already
        # yields in-band 4-timestamp offset samples (every traced
        # round-trip is one), which assume symmetric path delay
        self.ntp_servers = ntp_servers
        # ICI fast path (edge/devicechannel.py): probe whether the
        # server shares this process's device world — if so, device-
        # resident frames stay in HBM and only control metadata rides
        # the socket, both directions.  Falls back to plain TCP
        # transparently on any mismatch; device-channel=false never
        # probes.
        self.device_channel = device_channel
        # element-scoped fault injection on THIS link (grammar in
        # chaos/plan.py); the process-wide NNS_TPU_CHAOS plan applies
        # at the transport layer regardless
        self.chaos = chaos
        super().__init__(name, **props)
        self.add_sink_pad()
        self.add_src_pad()
        self._conn = None
        self._seq = 0
        self.dropped = 0
        self.timeouts = 0
        self.connected_addr = None  # (host, port) actually in use
        # per-peer clock-offset estimate fed by traced round-trips
        # (edge/ntputil.py): minimum-delay filter over recent exchanges
        self.peer_clock = PeerClock()
        self._metrics = None  # LinkMetrics of the live connection
        # the shared edge reconnect policy (chaos/retrypolicy.py):
        # jittered exponential backoff between failover sweeps + a
        # circuit breaker whose state exports on the LINK row
        self._retry = RetryPolicy(name=self.name, base_s=0.2, max_s=2.0,
                                  fail_threshold=6, open_s=2.0)
        self._chaos_plan = None  # parsed from chaos= in start()
        self._epoch_fn = async_ntp_epoch_fn(_parse_ntp_servers(ntp_servers)) \
            if str(ntp_servers or "").strip() else None
        self._clock_disagree = 0  # consecutive cross-check failures
        # seq → [input Buffer, reply Envelope|None, deadline, last-sent
        # conn]; insertion order IS stream order — replies flush from
        # the head.  An entry
        # with input None is an ordering TOMBSTONE: an expired request in
        # seq-less mode, kept one more timeout window so its late reply
        # is consumed in place instead of shifting every later seq-0
        # reply onto the wrong request.
        self._inflight: "OrderedDict[int, list]" = OrderedDict()
        # None until the first reply reveals the server's behavior:
        # True → server strips query_seq, replies pair FIFO (seq-less);
        # False → seqs are preserved, matching is exact.  While unknown,
        # expiry is conservative (tombstones) so a slow FIRST request
        # can't shift the pairing either way.
        self._seqless: Optional[bool] = None
        self._tomb_absorbs = 0  # seq-0 replies eaten by tombstones, unsettled
        self._cascade_cycles = 0  # absorb→expiry cycles (degradation signal)
        self._iflock = threading.Lock()
        self._pushing = 0  # answers popped but not yet pushed downstream
        self._connlock = threading.Lock()  # serializes conn swaps
        self._reader_run = threading.Event()
        self._reader_thread: Optional[threading.Thread] = None

    # -- connection -----------------------------------------------------------

    def _server_addrs(self):
        primary_port = int(self.dest_port or self.port)
        addrs = [(self.dest_host or self.host, primary_port)]
        for tok in str(self.alternate_hosts or "").split(","):
            tok = tok.strip()
            if not tok:
                continue
            h, _, p = tok.rpartition(":")
            # a bare hostname inherits the primary's port (port 0 would
            # make the failover entry unconditionally unreachable)
            addrs.append((h or tok,
                          int(p) if p.isdigit() else primary_port))
        return addrs

    def _attach_metrics(self, conn, host, port) -> None:
        """Bind the per-connection nns_edge_* stats: the element-level
        numbers (RTT, in-flight, timeouts) and the transport's byte
        counters share one LinkMetrics keyed by peer address, so the
        counters survive reconnects monotonically."""
        self._metrics = LinkMetrics.get(self.name, f"{host}:{port}",
                                        kind="query")
        conn.metrics = self._metrics
        self._retry.metrics = self._metrics
        self._retry._sync_metrics()

    def _probe_devch(self, conn, timeout: float = 1.0) -> None:
        """Device-channel handshake on a fresh connection (no-op when
        the element opted out): on success, device-resident frames ride
        the ICI fast path with only control metadata on the socket —
        else the connection stays in plain TCP framing."""
        if not bool(self.device_channel):
            return
        try:
            conn.request_devch(timeout=timeout)
        except Exception:  # noqa: BLE001 - probe must never kill connect
            pass

    def _ensure_conn(self):
        with self._connlock:
            if self._conn is None:
                errors = []
                for host, port in self._server_addrs():
                    try:
                        self._conn = connect(host, port, self.connect_type,
                                             topic=str(self.topic))
                        self.connected_addr = (host, port)
                        self._attach_metrics(self._conn, host, port)
                        self._probe_devch(self._conn)
                        break
                    except OSError as e:
                        errors.append(f"{host}:{port}: {e}")
                if self._conn is None:
                    raise NegotiationError(
                        f"{self.name}: no query server reachable "
                        f"({'; '.join(errors)})")
            return self._conn

    # -- negotiation ----------------------------------------------------------

    def pad_template_caps(self, pad: Pad) -> Caps:
        return Caps.any_tensors()

    def propose_src_caps(self, pad: Pad) -> Caps:
        from ..runtime.parser import parse_caps_string

        rate = self.sinkpad.spec.rate if self.sinkpad.spec else None
        if self.caps:
            return self.caps if isinstance(self.caps, Caps) \
                else parse_caps_string(str(self.caps))
        # ask the server what its pipeline outputs (registry caps exchange,
        # parity: tensor_query_server get/set caps)
        caps_str = self._ensure_conn().request_caps(timeout=2.0)
        if caps_str:
            try:
                return parse_caps_string(caps_str)
            except Exception:  # noqa: BLE001 - fall back to flexible
                logw("%s: unparseable server caps %r", self.name, caps_str)
        spec = TensorsSpec(format=TensorFormat.FLEXIBLE)
        if rate:
            spec = spec.with_rate(rate)
        return Caps.from_spec(spec)

    # -- hot path -------------------------------------------------------------

    def chain(self, pad: Pad, buf: Buffer) -> None:
        conn = self._ensure_conn()
        with self._iflock:
            live = sum(1 for e in self._inflight.values()
                       if e[0] is not None)
            if 0 < int(self.max_request) <= live:
                # server too slow: drop the input rather than queue
                # unboundedly (parity: max-request drop); tombstones
                # don't count — they hold ordering, not server work
                self.dropped += 1
                return
            self._seq += 1
            seq = self._seq
            now = time.monotonic()
            # entry: [input, reply, deadline, conn-last-sent-on,
            # send-time, resends] — the 4th field lets chain and the
            # failover resend coordinate so a request is never
            # DUPLICATED on the new connection (a seq-stripping server
            # would answer twice and the second seq-0 reply would shift
            # every later answer); the 5th times the round-trip and
            # doubles as the trace context's t1; the 6th caps mid-
            # stream retries at ONE — under repeated connection flaps
            # an already-resent request counts as a timeout instead of
            # riding (and stalling) every new connection
            self._inflight[seq] = [
                buf, None, now + float(self.timeout) / 1000.0, conn, now,
                0]
            self._update_inflight_locked()
        env = Envelope(MSG_QUERY, seq=seq, buffer=buf)
        if self.trace:
            tr = buf.meta.get(TRACE_META_KEY)
            if tr is not None:
                env.trace = tracectx.request_ctx(tr, now)
        ch = self._chaos_plan
        if ch is not None:
            # element-scoped wire faults on the REQUEST path (the
            # process-wide plan already applies inside the transport)
            op = ch.wire(self.name, "tx", env)
            if op is not None:
                # disconnect: the reader sees a dead conn → failover
                apply_wire_op(op, conn.send, conn.close)
                return  # dropped frames surface as timeouts, never lost
        if not conn.send(env):
            # Serialize against a failover in flight: taking _connlock
            # waits until its resend snapshot has run, so either it
            # already resent this entry IN ORDER with the older seqs
            # (entry now tagged with the new conn → we skip) or it
            # finished before this entry existed (we send — again in
            # order, after all its resends).  Sending without this wait
            # could put the NEWEST seq on the wire before the older
            # resends, mispairing seq-less (FIFO) replies.
            with self._connlock:
                cur = self._conn
            if cur is not None and cur is not conn:
                with self._iflock:
                    ent = self._inflight.get(seq)
                    resend = ent is not None and ent[3] is conn
                    if resend:
                        ent[3] = cur
                if resend:
                    cur.send(env)
            else:
                # connection died under us: the entry stays in flight and
                # the reader thread's failover resends it
                logw("%s: send failed, awaiting failover", self.name)

    def _reply_arrived(self, ent, env, t4: float) -> None:
        """Attach a reply to its in-flight entry (caller holds
        ``_iflock``): record the round-trip, and — when the reply
        carries a trace context — absorb the server's spans into the
        input buffer's trace, feeding the per-exchange clock offset
        into :attr:`peer_clock`."""
        ent[1] = env
        rtt = t4 - ent[4]
        if self._metrics is not None:
            self._metrics.observe_rtt(rtt)
        if env.trace is not None and ent[0] is not None:
            tr = ent[0].meta.get(TRACE_META_KEY)
            if tr is not None:
                est = tracectx.absorb_reply(tr, env.trace, t4,
                                            link=self.name)
                if est is not None:
                    self.peer_clock.add(*est)
                    self._clock_cross_check(env.trace, est)

    def _clock_cross_check(self, ctx, est) -> None:
        """``ntp-servers=`` wall-clock cross-check of the in-band span
        placement: wall clocks say the reply left the server
        ``lag_wall`` before now, the in-band estimate says ``delay/2``.
        A persistent gap beyond the error budget means the network path
        is asymmetric (or a clock is unsynchronized) and remote spans
        are skewed — exactly what lint NNS506 warns about when no NTP
        is configured.  The epoch callable is the async (arithmetic-
        only) variant, so this is safe on the reply path."""
        epoch3 = ctx.get("epoch3_us")
        if self._epoch_fn is None or not isinstance(epoch3, (int, float)):
            return
        offset, delay = est
        lag_wall = (self._epoch_fn() - float(epoch3)) / 1e6
        if abs(lag_wall - delay / 2.0) <= max(delay, 0.005):
            self._clock_disagree = 0
            return
        self._clock_disagree += 1
        if self._clock_disagree == 5:  # persistent, not a one-off spike
            self._clock_disagree = 0
            logw("%s: NTP wall clocks disagree with the in-band span "
                 "placement by %.1f ms (rtt %.1f ms) — asymmetric "
                 "network path or unsynchronized server clock; remote "
                 "trace spans may be skewed", self.name,
                 abs(lag_wall - delay / 2.0) * 1e3, delay * 1e3)

    def start(self) -> None:
        if str(self.chaos or "").strip():
            from ..chaos.plan import FaultPlan

            self._chaos_plan = FaultPlan.parse(str(self.chaos))
        self._reader_run.set()
        from ..obs import prof as _prof

        self._reader_thread = _prof.named_thread(
            "edge-replies", self.name, self._reader_loop)
        self._reader_thread.start()
        super().start()

    def _reader_loop(self) -> None:
        while self._reader_run.is_set():
            conn = self._conn
            if conn is None:
                time.sleep(0.02)
                continue
            env = conn.recv(timeout=0.1)
            envs = [env] if env is not None else []
            ch = self._chaos_plan
            if envs and ch is not None:
                # element-scoped wire faults on the REPLY path
                op = ch.wire(self.name, "rx", env)
                if op is not None:
                    envs = []
                    apply_wire_op(op, envs.append,
                                  conn.close)
            for e in envs:
                if e.mtype == MSG_REPLY:
                    self._process_reply(e, time.monotonic())
                    self._flush_ready()
            self._expire(time.monotonic())
            if env is None and not conn.is_alive():
                self._failover(conn)
                # compensate for the flush=False expiries inside the
                # failover window: a head removal there can leave
                # completed replies parked with no future event to
                # flush them (e.g. out-of-order B answered, A expired)
                self._flush_ready()

    def _process_reply(self, env: Envelope, t4: float) -> None:
        """Match one MSG_REPLY against the in-flight order (exact by
        seq, else arrival-order with tombstone absorption)."""
        with self._iflock:
            if env.seq != 0:
                ent = self._inflight.get(env.seq)
                if ent is not None:
                    if ent[0] is None:
                        # a tombstoned request's own seq'd reply: too
                        # late to deliver, but proof the server
                        # preserves seqs — consume the tombstone so it
                        # stops parking later completed replies
                        del self._inflight[env.seq]
                    else:
                        self._reply_arrived(ent, env, t4)
                    if self._seqless is not False:
                        # seqs are flowing (again): exact matching
                        # needs no ordering tombstones — purge any
                        # left from the unknown/seq-less phase so
                        # they don't park completed replies behind
                        # a dead head entry
                        self._seqless = False
                        self._purge_tombstones_locked()
            elif self._inflight:
                # server pipeline lost the query_seq meta: fall
                # back to arrival-order matching (oldest pending)
                self._seqless = True
                for seq, e in self._inflight.items():
                    if e[1] is not None:
                        continue
                    if e[0] is None:
                        # tombstone of an expired request: treat
                        # this as its late reply — consume &
                        # discard so the NEXT reply pairs with
                        # the right request instead of shifting
                        # by one.  If the absorbed reply was in
                        # fact a live request's on-time answer
                        # (the server silently DROPPED the
                        # tombstone's query — indistinguishable
                        # from a stall, see _expire), that victim
                        # surfaces as a visible timeout and the
                        # absorb→expiry cycle counter raises a
                        # loud diagnostic.
                        del self._inflight[seq]
                        self._tomb_absorbs += 1
                    else:
                        self._reply_arrived(e, env, t4)
                        self._tomb_absorbs = 0
                        self._cascade_cycles = 0
                    break

    def _flush_ready(self) -> None:
        """Pop completed requests from the HEAD of the in-flight order and
        push their answers — replies may complete out of order, buffers
        still leave in stream order.  ``_pushing`` stays non-zero from pop
        to push so ``on_eos`` cannot see "drained" between the two and
        let EOS overtake the final buffer."""
        while True:
            with self._iflock:
                if not self._inflight:
                    return
                seq = next(iter(self._inflight))
                ent = self._inflight[seq]
                if ent[1] is None:
                    return
                self._inflight.popitem(last=False)
                self._update_inflight_locked()
                self._pushing += 1
            try:
                inbuf, env = ent[0], ent[1]
                out = env.buffer
                if out is None:
                    continue
                # metadata comes from the *incoming* buffer (reference
                # copies GST_BUFFER_COPY_METADATA from input onto answer);
                # the trace key stays the CLIENT's — over inproc the
                # answer still carries the server pipeline's own planted
                # trace dict, which must not shadow the local one
                out = dataclasses.replace(
                    out, pts=inbuf.pts, duration=inbuf.duration,
                    offset=inbuf.offset,
                    meta={**inbuf.meta,
                          **{k: v for k, v in out.meta.items()
                             if k not in ("client_id", "query_seq",
                                          TRACE_META_KEY)}})
                self.push(out)
            finally:
                with self._iflock:
                    self._pushing -= 1

    def _update_inflight_locked(self) -> None:
        """Refresh the nns_edge_inflight gauge (caller holds _iflock);
        tombstones hold ordering, not server work, so they don't count."""
        if self._metrics is not None:
            self._metrics.set_inflight(sum(
                1 for e in self._inflight.values()
                if e[0] is not None and e[1] is None))

    def _purge_tombstones_locked(self) -> int:
        """Drop every ordering tombstone (caller holds ``_iflock``).
        Returns how many were removed — a removed HEAD tombstone can
        unblock completed replies, so callers re-run ``_flush_ready``
        (outside the lock) when this is non-zero."""
        stale = [s for s, e in self._inflight.items()
                 if e[0] is None and e[1] is None]
        for s in stale:
            del self._inflight[s]
        return len(stale)

    def _expire(self, now: float, flush: bool = True) -> None:
        """``flush=False`` for callers holding ``_connlock``:
        _flush_ready pushes downstream, which must never happen under
        that lock (chain's _ensure_conn path) — the reader loop re-runs
        _expire with flushing right after failover returns."""
        expired, removed = [], 0
        with self._iflock:
            for seq, ent in list(self._inflight.items()):
                if ent[1] is not None or ent[2] > now:
                    continue
                if ent[0] is not None and self._seqless is not False:
                    # seq-less replies pair by arrival order: leave an
                    # ordering tombstone for one more window so the late
                    # reply (if any) is absorbed in place.  This is the
                    # correctness-safe choice for BOTH failure stories —
                    # a slow server (each tombstone absorbs its own late
                    # answer, stream recovers) and a query-dropping
                    # server (each tombstone eats the NEXT on-time
                    # answer; frames are discarded as visible timeouts,
                    # never silently mispaired).  The two are
                    # indistinguishable from the client, so the dropping
                    # case cannot be "fixed" without risking mispaired
                    # data; it is surfaced via _cascade_cycles below.
                    if self._tomb_absorbs > 0:
                        self._tomb_absorbs -= 1
                        self._cascade_cycles += 1
                    ent[0] = None
                    ent[2] = now + float(self.timeout) / 1000.0
                    expired.append(seq)
                elif ent[0] is not None:
                    expired.append(seq)
                    del self._inflight[seq]
                    removed += 1
                else:
                    # tombstone past its grace window: no reply is coming
                    # (e.g. the server dropped the query) — removing it
                    # cannot shift pairing
                    del self._inflight[seq]
                    removed += 1
            if expired or removed:
                self._update_inflight_locked()
        for seq in expired:
            self.timeouts += 1
            if self._metrics is not None:
                self._metrics.timeout()
            logw("%s: no answer for request %d within %sms",
                 self.name, seq, self.timeout)
        if self._cascade_cycles >= 3:
            # absorb→expiry cycles are self-sustaining: either the
            # server pipeline is persistently slower than `timeout` or
            # it silently drops queries — both deliver zero frames in
            # seq-less mode and the client cannot tell them apart
            self._cascade_cycles = 0
            loge("%s: seq-less reply pairing is degraded — the query "
                 "server strips query_seq meta AND answers are "
                 "persistently late or missing; frames are being "
                 "dropped.  Preserve query_seq meta in the server "
                 "pipeline or raise timeout= (current %sms)",
                 self.name, self.timeout)
        if removed and flush:
            # any head removal can unblock later already-completed
            # replies (incl. seq'd replies parked behind a tombstone)
            self._flush_ready()

    def _failover(self, dead) -> None:
        """Mid-stream reconnect: try every configured address — the one
        that just died last (its server may have restarted) — and resend
        whatever is still in flight on the new connection."""
        dropped_tomb = False
        reconnected = False
        errors = []
        spent: list = []
        with self._connlock:
            if self._conn is not dead:
                return  # someone else already failed over
            try:
                dead.close()
            except Exception:  # noqa: BLE001
                pass
            self._conn = None
            addrs = self._server_addrs()
            if self.connected_addr in addrs:
                addrs = [a for a in addrs if a != self.connected_addr] + \
                    [self.connected_addr]
            # Retry window: long enough to ride out a restarting server.
            # For hybrid this must cover at least one advertise interval
            # (2 s) — a replacement server can't overwrite the dead
            # server's stale retained advertisement any faster, and
            # erroring out before it does would defeat re-discovery.
            # Capped at 10 s: _connlock is held throughout (chain()
            # blocks in _ensure_conn), so the window must not scale with
            # a large `timeout` (30 s XLA-compile timeouts would stall
            # upstream that long on a permanently dead server).
            retry_deadline = time.monotonic() + min(
                max(3.0, float(self.timeout) / 1000.0), 10.0)
            attempt = 0
            # the deadline (not an attempt count) bounds the loop, and
            # each connect gets a short timeout — a hybrid discovery
            # against an unregistered topic would otherwise block its
            # full 5 s per address and blow through the cap
            while not reconnected and time.monotonic() < retry_deadline:
                if attempt:
                    # jittered exponential backoff between sweeps — the
                    # shared edge retry policy (chaos/retrypolicy.py)
                    # replaces the old fixed-rate 0.3 s hammer; capped
                    # so the sweeps still fit the failover window
                    # nns-lint: disable=NNS602 -- deliberate: _connlock
                    # IS the failover critical section (senders MUST
                    # block until a live conn exists or the window
                    # expires); the wait is capped at 10 s above
                    self._retry.wait(max_s=max(
                        retry_deadline - time.monotonic(), 0.05))
                    # deadlines keep passing while we hold _connlock:
                    # surface per-request timeouts (only takes _iflock —
                    # lock order _connlock → _iflock holds; no flush
                    # under _connlock, the reader loop flushes next)
                    self._expire(time.monotonic(), flush=False)
                attempt += 1
                for host, port in addrs:
                    # re-check between addresses too: each blocking
                    # connect can cost seconds, and a long alternate
                    # list would otherwise hold _connlock far past the
                    # cap (first sweep always tries every address)
                    if attempt > 1 and \
                            time.monotonic() >= retry_deadline:
                        break
                    try:
                        conn = connect(host, port, self.connect_type,
                                       timeout=2.5,  # > advertise tick
                                       topic=str(self.topic))
                    except OSError as e:
                        errors.append(f"{host}:{port}: {e}")
                        continue
                    self._conn = conn
                    self.connected_addr = (host, port)
                    self._attach_metrics(conn, host, port)
                    # re-probe: the replacement server may be a
                    # different process (no shared device world)
                    self._probe_devch(conn)
                    self._metrics.reconnect()
                    self._retry.success()
                    # a different server means a different clock: old
                    # offset samples no longer apply
                    self.peer_clock = PeerClock()
                    with self._iflock:
                        # a different server may strip (or preserve) seqs
                        # differently — re-learn, staying conservative
                        self._seqless = None
                        self._tomb_absorbs = 0
                        self._cascade_cycles = 0
                        # tombstones: their late replies died with the
                        # old connection
                        dropped_tomb = self._purge_tombstones_locked() > 0
                        now = time.monotonic()
                        pending = []
                        for seq, ent in self._inflight.items():
                            if ent[1] is not None:
                                continue
                            if ent[3] is conn:
                                # chain()'s failed-send fallback already
                                # sent this one on the NEW connection —
                                # resending would duplicate the query
                                # (two seq-0 answers shift the pairing)
                                continue
                            if ent[5] >= 1:
                                # already resent on an earlier reconnect:
                                # at most ONE mid-stream retry per
                                # request — under repeated flaps the
                                # old deadline-extension made an entry
                                # immortal (stalling EOS and double-
                                # counting server work); it now counts
                                # as a timeout instead
                                spent.append(seq)
                                continue
                            # reconnecting may have outlived the original
                            # deadline (set at enqueue): restart the clock
                            # so the resends aren't immediately expired as
                            # spurious timeouts while the server redoes
                            # the work
                            ent[2] = now + float(self.timeout) / 1000.0
                            # tag with the new conn so chain()'s failed-
                            # send fallback knows not to duplicate it
                            ent[3] = conn
                            ent[4] = now  # RTT clock restarts with the resend
                            ent[5] += 1
                            pending.append((seq, ent[0]))
                        for seq in spent:
                            del self._inflight[seq]
                        if spent or pending:
                            self._update_inflight_locked()
                    for seq in spent:
                        self.timeouts += 1
                        if self._metrics is not None:
                            self._metrics.timeout()
                    if spent:
                        logw("%s: %d request(s) dropped after a second "
                             "connection loss (resent at most once)",
                             self.name, len(spent))
                    for seq, buf in pending:
                        conn.send(Envelope(MSG_QUERY, seq=seq, buffer=buf))
                    logw("%s: failed over to %s:%s (%d requests resent)",
                         self.name, host, port, len(pending))
                    reconnected = True
                    break
                if not reconnected:
                    # one failure per SWEEP (not per address): the
                    # backoff/breaker tracks the outage, not the length
                    # of the alternate list
                    self._retry.failure(
                        errors[-1] if errors else "unreachable",
                        what="failover reconnect")
        if reconnected:
            if dropped_tomb or spent:
                # a removed head tombstone can unblock completed replies
                # parked behind it — same invariant as _expire.  Flushed
                # AFTER releasing _connlock: _flush_ready pushes
                # downstream, and a full sink would otherwise hold the
                # lock against chain() → _ensure_conn() (deadlock).
                self._flush_ready()
            return
        self.post_error(StreamError(
            f"{self.name}: connection lost and no server reachable "
            f"({'; '.join(errors)})"))
        self._reader_run.clear()

    def on_eos(self) -> None:
        """Drain in-flight requests before EOS propagates (answers still
        on the wire must not be cut off by downstream teardown)."""
        deadline = time.monotonic() + float(self.timeout) / 1000.0
        while time.monotonic() < deadline:
            with self._iflock:
                if not self._pushing and all(
                        e[0] is None and e[1] is None
                        for e in self._inflight.values()):
                    # empty, or ordering tombstones only: tombstones
                    # hold no deliverable data and there is nothing
                    # behind them to protect — don't stall EOS a full
                    # grace window for them
                    self._inflight.clear()
                    return
            time.sleep(0.005)

    def stop(self) -> None:
        self._reader_run.clear()
        if self._reader_thread is not None:
            self._reader_thread.join(timeout=2.0)
            self._reader_thread = None
        if self._conn is not None:
            self._conn.close()
            self._conn = None
        if self._epoch_fn is not None:
            self._epoch_fn.stop()  # retire the SNTP refresh thread
        with self._iflock:
            self._inflight.clear()


# -- server source ------------------------------------------------------------


@register_element("tensor_query_serversrc")
class TensorQueryServerSrc(SourceElement):
    """Entry of the server pipeline: owns the transport, stamps queries
    with ``client_id`` routing meta."""

    FACTORY = "tensor_query_serversrc"

    def __init__(self, name=None, host: str = "localhost", port: int = 0,
                 connect_type: str = "tcp", id: int = 0, caps=None,
                 num_buffers: int = -1, topic: str = "",
                 data_host: str = "127.0.0.1", data_port: int = 0,
                 advertise_host: str = "", **props):
        self.host = host
        self.port = port
        self.connect_type = connect_type
        self.topic = topic  # hybrid: registered at the broker (host:port)
        # hybrid data plane: bind data_host:data_port (0.0.0.0/0 for
        # cross-host), advertise advertise_host when the bind address
        # isn't what clients should dial
        self.data_host = data_host
        self.data_port = data_port
        self.advertise_host = advertise_host
        self.id = id
        self.caps = caps
        self.num_buffers = num_buffers
        super().__init__(name, **props)
        if isinstance(self.caps, str):
            from ..runtime.parser import parse_caps_string

            self.caps = parse_caps_string(self.caps)
        self._queue: "queue.Queue[Envelope]" = queue.Queue(maxsize=64)
        self._server = None
        self._count = 0

    def output_spec(self) -> TensorsSpec:
        if self.caps is not None:
            return self.caps.to_spec()
        return TensorsSpec(format=TensorFormat.FLEXIBLE)

    def _on_message(self, client_id: int, env: Envelope) -> None:
        if env.mtype != MSG_QUERY or env.buffer is None:
            return
        if env.trace is not None:
            # t2 of the NTP-style exchange: stamped at transport
            # delivery, before any queueing in the server pipeline
            env.trace["t2"] = time.monotonic()
        try:
            self._queue.put_nowait(env)
        except queue.Full:
            logw("%s: query queue full, dropping client %d request",
                 self.name, client_id)

    def start(self) -> None:
        entry = query_server_entry(int(self.id))
        if self._server is None:
            self._server = make_server(self.host, int(self.port),
                                       self.connect_type,
                                       topic=str(self.topic),
                                       data_host=str(self.data_host),
                                       data_port=int(self.data_port),
                                       advertise_host=str(
                                           self.advertise_host))
            self._server.on_message = self._on_message
            self._server.caps_provider = lambda: entry.sink_caps
            self._server.start()
            # expose the actual port (port=0 binds an ephemeral one;
            # for hybrid this is the DATA port, host:port stays broker)
            if self.connect_type != "hybrid":
                self.port = getattr(self._server, "port", self.port)
            # after the bind so the peer label carries the real port
            # (no client can dial in before the port is known anyway)
            self._server.metrics = LinkMetrics.get(
                self.name, f"{self.host}:{self.port}", kind="query-server")
        entry.transport = self._server
        super().start()

    def stop(self) -> None:
        super().stop()
        if self._server is not None:
            self._server.stop()
            entry = query_server_entry(int(self.id))
            if entry.transport is self._server:
                entry.transport = None
            self._server = None

    def create(self) -> Optional[Buffer]:
        if 0 <= int(self.num_buffers) <= self._count:
            return None
        while self._running.is_set():
            try:
                env = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            self._count += 1
            buf = env.buffer
            # shallow-copy: never mutate the client's buffer (inproc
            # passes it by reference)
            buf = dataclasses.replace(buf, meta=dict(buf.meta))
            buf.meta["client_id"] = env.client_id
            buf.meta["query_seq"] = env.seq
            if env.trace is not None:
                # continue the client's trace in THIS process: the
                # planted dict collects hook marks through the server
                # pipeline and serversink echoes them in the reply
                tracectx.plant_server_trace(buf.meta, env.trace,
                                            self.name)
            return buf
        return None


# -- server sink --------------------------------------------------------------


@register_element("tensor_query_serversink")
class TensorQueryServerSink(SinkElement):
    """Exit of the server pipeline: routes each answer to the client that
    asked, via the ``client_id`` meta."""

    FACTORY = "tensor_query_serversink"

    def __init__(self, name=None, id: int = 0,
                 metaless_frame_limit: int = 2, **props):
        self.id = id
        self.metaless_frame_limit = metaless_frame_limit
        super().__init__(name, **props)
        self._metaless = 0

    def caps_negotiated(self, pad: Pad) -> None:
        # register the server pipeline's output caps so clients can
        # negotiate against them (parity: serversink set_caps →
        # gst_tensor_query_server_set_caps)
        if pad.caps is not None:
            query_server_entry(int(self.id)).sink_caps = str(pad.caps)

    def render(self, buf: Buffer) -> None:
        client_id = buf.meta.get("client_id")
        if client_id is None:
            self._metaless += 1
            logw("%s: no client_id meta on buffer — an element in the "
                 "server pipeline dropped routing meta", self.name)
            if self._metaless >= int(self.metaless_frame_limit):
                raise StreamError(
                    f"{self.name}: {self._metaless} metaless frames; "
                    "check elements used in the query-server pipeline")
            return
        self._metaless = 0
        entry = query_server_entry(int(self.id))
        if entry.transport is None:
            raise StreamError(
                f"{self.name}: no serversrc transport for id={self.id}")
        # echo a remote-origin trace back to the requester: marks
        # collected server-side + t2/t3 for its clock alignment
        ctx = tracectx.reply_ctx(buf.meta.get(TRACE_META_KEY))
        entry.transport.send(
            int(client_id),
            Envelope(MSG_REPLY, client_id=int(client_id),
                     seq=int(buf.meta.get("query_seq", 0)), buffer=buf,
                     trace=ctx))


# -- edge pub/sub -------------------------------------------------------------


@register_element("edgesink")
class EdgeSink(SinkElement):
    """Publish a tensor stream: subscribers (edgesrc) receive every
    rendered buffer for their topic.

    Parity: /root/reference/gst/edge/edge_sink.c:291-334 (nns_edge server
    publishing over TCP/HYBRID with ``topic``)."""

    FACTORY = "edgesink"

    def __init__(self, name=None, host: str = "localhost", port: int = 0,
                 connect_type: str = "tcp", topic: str = "",
                 data_host: str = "127.0.0.1", data_port: int = 0,
                 advertise_host: str = "", ntp_servers: str = "",
                 **props):
        self.host = host
        self.port = port
        self.connect_type = connect_type
        self.topic = topic
        self.data_host = data_host          # hybrid data-plane bind
        self.data_port = data_port
        self.advertise_host = advertise_host
        # one-way hop: trace alignment leans on wall clocks — with NTP
        # servers configured the epoch stamp is disciplined, otherwise
        # it is the local clock (subscriber-side spans may skew)
        self.ntp_servers = ntp_servers
        super().__init__(name, **props)
        self._server = None
        self.published = 0
        self._epoch_fn = async_ntp_epoch_fn(_parse_ntp_servers(ntp_servers)) \
            if str(ntp_servers or "").strip() else None

    def _epoch_us(self) -> int:
        return int(self._epoch_fn()) if self._epoch_fn is not None \
            else int(time.time() * 1e6)

    def start(self) -> None:
        if self._server is None:
            self._server = make_server(self.host, int(self.port),
                                       self.connect_type,
                                       topic=str(self.topic),
                                       data_host=str(self.data_host),
                                       data_port=int(self.data_port),
                                       advertise_host=str(
                                           self.advertise_host))
            self._server.caps_provider = lambda: (
                str(self.sinkpad.caps) if self.sinkpad.caps else "")
            self._server.start()
            if self.connect_type != "hybrid":
                self.port = getattr(self._server, "port", self.port)
            # after the bind so the peer label carries the real port
            self._server.metrics = LinkMetrics.get(
                self.name, f"{self.host}:{self.port}", kind="edge-pub")

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop()
            self._server = None
        if self._epoch_fn is not None:
            self._epoch_fn.stop()

    def render(self, buf: Buffer) -> None:
        if self._server is None:
            raise StreamError(f"{self.name}: not started")
        env = Envelope(MSG_PUBLISH, info=str(self.topic), buffer=buf)
        tr = buf.meta.get(TRACE_META_KEY)
        if tr is not None:
            env.trace = tracectx.oneway_ctx(tr, self._epoch_us())
        self.published += self._server.publish(env)


@register_element("edgesrc")
class EdgeSrc(SourceElement):
    """Subscribe to a published tensor stream by topic.

    Parity: /root/reference/gst/edge/edge_src.c (nns_edge client with
    ``dest-host``/``dest-port``/``topic``)."""

    FACTORY = "edgesrc"

    def __init__(self, name=None, dest_host: str = "localhost",
                 dest_port: int = 0, connect_type: str = "tcp",
                 topic: str = "", caps=None, num_buffers: int = -1,
                 ntp_servers: str = "", reconnect: bool = True,
                 reconnect_timeout_s: float = 30.0,
                 device_channel: bool = True, **props):
        self.dest_host = dest_host
        self.dest_port = dest_port
        self.connect_type = connect_type
        self.topic = topic
        self.caps = caps
        self.num_buffers = num_buffers
        # NTP-disciplined local epoch for one-way trace alignment (the
        # publisher should configure the same; see edgesink)
        self.ntp_servers = ntp_servers
        # self-healing: a dead publisher connection re-dials (and
        # re-subscribes) through the shared backoff/breaker policy
        # instead of spinning on a dead socket forever; an outage
        # longer than reconnect-timeout-s becomes a clean bus error
        self.reconnect = reconnect
        self.reconnect_timeout_s = reconnect_timeout_s
        # ICI fast path: announce our device fingerprint to the
        # publisher — on a match, published device-resident frames stay
        # in HBM and only control frames ride the subscription socket
        # (transparent TCP fallback otherwise; see edgesink)
        self.device_channel = device_channel
        super().__init__(name, **props)
        if isinstance(self.caps, str):
            from ..runtime.parser import parse_caps_string

            self.caps = parse_caps_string(self.caps)
        self._conn = None
        self._count = 0
        self._metrics = None
        self._retry = RetryPolicy(name=self.name, base_s=0.2, max_s=2.0,
                                  fail_threshold=6, open_s=2.0)
        self._epoch_fn = async_ntp_epoch_fn(_parse_ntp_servers(ntp_servers)) \
            if str(ntp_servers or "").strip() else None

    def _epoch_us(self) -> int:
        return int(self._epoch_fn()) if self._epoch_fn is not None \
            else int(time.time() * 1e6)

    def _ensure_conn(self):
        if self._conn is None:
            self._conn = connect(self.dest_host, int(self.dest_port),
                                 self.connect_type, topic=str(self.topic))
            self._metrics = LinkMetrics.get(
                self.name, f"{self.dest_host}:{self.dest_port}",
                kind="edge-sub")
            self._conn.metrics = self._metrics
            self._retry.metrics = self._metrics
            self._retry._sync_metrics()
            self._conn.send(Envelope(MSG_SUBSCRIBE, info=str(self.topic)))
            if bool(self.device_channel):
                try:
                    self._conn.request_devch()
                except Exception:  # noqa: BLE001 - probe never kills
                    pass  # the subscription; plain TCP continues
        return self._conn

    def _reconnect(self, dead) -> Optional[object]:
        """Publisher gone mid-stream: re-dial + re-subscribe through
        the shared retry policy (backoff + breaker) until it answers,
        stop() interrupts, or the outage outlives
        ``reconnect-timeout-s`` (→ StreamError on the bus)."""
        try:
            dead.close()
        except Exception:  # noqa: BLE001
            pass
        self._conn = None
        deadline = time.monotonic() + float(self.reconnect_timeout_s)
        while self._running.is_set():
            if time.monotonic() >= deadline:
                raise StreamError(
                    f"{self.name}: publisher unreachable for "
                    f"{self.reconnect_timeout_s}s (gave up reconnecting)")
            if not self._retry.wait(max_s=max(
                    deadline - time.monotonic(), 0.05)):
                return None
            if not self._running.is_set():
                return None
            try:
                conn = self._ensure_conn()
            except OSError as e:
                self._retry.failure(e, what="re-subscribe")
                continue
            self._retry.success()
            if self._metrics is not None:
                self._metrics.reconnect()
            return conn
        return None

    def output_spec(self) -> TensorsSpec:
        if self.caps is not None:
            return self.caps.to_spec()
        from ..runtime.parser import parse_caps_string

        caps_str = self._ensure_conn().request_caps(timeout=2.0)
        if caps_str:
            try:
                return parse_caps_string(caps_str).to_spec()
            except Exception:  # noqa: BLE001
                logw("%s: unparseable publisher caps %r", self.name,
                     caps_str)
        return TensorsSpec(format=TensorFormat.FLEXIBLE)

    def start(self) -> None:
        self._ensure_conn()
        super().start()

    def stop(self) -> None:
        super().stop()
        if self._conn is not None:
            self._conn.close()
            self._conn = None
        if self._epoch_fn is not None:
            self._epoch_fn.stop()

    def create(self) -> Optional[Buffer]:
        if 0 <= int(self.num_buffers) <= self._count:
            return None
        conn = self._ensure_conn()
        while self._running.is_set():
            env = conn.recv(timeout=0.1)
            if env is None:
                if bool(self.reconnect) and not conn.is_alive():
                    conn = self._reconnect(conn)
                    if conn is None:
                        return None
                continue
            if env.mtype != MSG_PUBLISH or env.buffer is None:
                continue
            self._count += 1
            buf = env.buffer
            if env.trace is not None and _hooks.tracer is not None:
                # inproc publish shares the buffer object: never mutate
                # the publisher's meta in place
                buf = dataclasses.replace(buf, meta=dict(buf.meta))
                tracectx.plant_oneway(buf.meta, env.trace,
                                      self._epoch_us(), link=self.name,
                                      source_name=self.name)
            return buf
        return None

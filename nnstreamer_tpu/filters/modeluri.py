"""Model-URI resolution hook (ML-Agent analog).

Parity target: /root/reference/gst/nnstreamer/ml_agent.c (156 LoC):
``mlagent://model/<name>/<version>`` URIs in the ``model=`` property are
resolved to real model paths through the platform's model database
before the filter opens them.

Here the scheme→resolver mapping is pluggable: a deployment registers a
resolver for its model registry (an on-disk store, an artifact service,
…) and every ``tensor_filter``/``FilterSingle`` resolves URIs before
framework detection.  A built-in ``file://`` resolver is registered.

Versioned model references (``runtime/lifecycle.py`` provenance): a
``@<tag>`` suffix on a path/URI names one version of a model —
``file://models/net.pkl@v2`` (a tagged file), ``ckpts/net@123`` /
``ckpts/net@latest`` (an orbax step directory under a checkpoint
root).  :func:`resolve_model_uri_versioned` resolves to ``(model,
version-tag)`` so every hot swap carries WHICH version went live into
the audit ring; an unresolvable version suffix raises a clear
:class:`ModelUriError` naming the suffix instead of a bare
FileNotFoundError from whatever opener tripped over the ``@``.
"""

from __future__ import annotations

import os
import re
import threading
from typing import Any, Callable, Dict, Tuple
from urllib.parse import urlparse

_lock = threading.Lock()
_resolvers: Dict[str, Callable[[str], Any]] = {}

#: version-tag grammar: word chars, dots and dashes after a final ``@``
_VERSION_RE = re.compile(r"^(?P<base>.+)@(?P<tag>[A-Za-z0-9._-]+)$")


class ModelUriError(ValueError):
    """A model URI/path that cannot resolve — bad scheme, missing
    target, or a version suffix naming nothing."""


def register_model_resolver(scheme: str,
                            fn: Callable[[str], Any]) -> None:
    """``fn(uri) -> model`` (a path or any model object the target
    framework accepts)."""
    with _lock:
        _resolvers[scheme.lower()] = fn


def unregister_model_resolver(scheme: str) -> None:
    with _lock:
        _resolvers.pop(scheme.lower(), None)


def split_model_version(model: Any) -> Tuple[Any, str]:
    """Split a trailing ``@<tag>`` version suffix off a string model
    reference: ``("models/net.pkl@v2")`` → ``("models/net.pkl",
    "v2")``.  A string that names an existing file AS-IS never splits
    (a file literally called ``x@y.pkl`` keeps working); non-strings
    pass through untagged."""
    if not isinstance(model, str):
        return model, ""
    m = _VERSION_RE.match(model)
    if m is None or os.path.exists(model):
        return model, ""
    return m.group("base"), m.group("tag")


def _resolve_scheme(model: str) -> Any:
    scheme = urlparse(model).scheme.lower()
    with _lock:
        fn = _resolvers.get(scheme)
    if fn is None:
        raise KeyError(
            f"no model resolver for scheme {scheme!r} "
            f"(register one with register_model_resolver)")
    return fn(model)


def resolve_model_uri_versioned(model: Any) -> Tuple[Any, str]:
    """Resolve a (possibly versioned) model reference to ``(model,
    version-tag)`` — the provenance pair the lifecycle layer records
    in the audit ring on every hot swap.

    - ``file://models/net.pkl@v2`` → ``("models/net.pkl", "v2")`` —
      the tag is provenance; the file must exist;
    - ``ckpts/net@123`` / ``ckpts/net@latest`` → the orbax step
      DIRECTORY under the checkpoint root (``trainers/checkpoint.py``
      step layout) and the concrete step as the tag;
    - untagged references resolve exactly like
      :func:`resolve_model_uri` with tag ``""``.

    A version suffix that names nothing raises :class:`ModelUriError`
    carrying the suffix and the base it was split from — not a bare
    FileNotFoundError from the opener."""
    if isinstance(model, (list, tuple)):
        return (type(model)(resolve_model_uri(m) for m in model)), ""
    if not isinstance(model, str):
        return model, ""
    scheme = "://" in model
    base, tag = split_model_version(model)
    if not scheme and tag and not os.path.exists(str(base)):
        # a plain string whose '@'-base names nothing on disk is a
        # NAME (an in-process registered model of ANY framework may
        # legally contain '@') — pass it through untouched, exactly
        # as before versioned references existed; the framework's own
        # open error covers real typos
        return model, ""
    if scheme:
        base = _resolve_scheme(base)
    if not tag:
        return base, ""
    if isinstance(base, str) and os.path.isdir(base):
        # orbax checkpoint root: the tag names a step directory
        from ..trainers.checkpoint import resolve_step_dir

        try:
            return resolve_step_dir(base, tag)
        except ValueError as e:
            raise ModelUriError(
                f"model {model!r}: version suffix @{tag} does not "
                f"resolve under checkpoint root {base!r}: {e}") from None
    if isinstance(base, str) and not os.path.exists(base):
        # scheme-qualified references are EXPLICIT paths: a version
        # suffix naming nothing is a clear error, not a bare
        # FileNotFoundError from the opener
        raise ModelUriError(
            f"model {model!r}: version suffix @{tag} was split off, "
            f"but {base!r} does not exist — versioned references need "
            f"the base file/checkpoint-root on disk")
    return base, tag


def resolve_model_uri(model: Any) -> Any:
    """Resolve scheme-qualified string models; multi-file model lists
    resolve per entry; everything else passes through untouched.
    Versioned references (``@tag`` suffixes) resolve to their target
    with the tag dropped — :func:`resolve_model_uri_versioned` returns
    the tag too."""
    if isinstance(model, (list, tuple)):
        return type(model)(resolve_model_uri(m) for m in model)
    if not isinstance(model, str):
        return model
    if "://" not in model and split_model_version(model)[1] == "":
        return model
    return resolve_model_uri_versioned(model)[0]


def _file_resolver(uri: str) -> str:
    return urlparse(uri).path


register_model_resolver("file", _file_resolver)

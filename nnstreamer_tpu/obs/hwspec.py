"""Hardware peak table — the denominator of every utilization figure.

MFU and HBM-bandwidth utilization are ratios against *hardware* peaks,
which until now lived as loose ``V5E_*`` constants inside ``bench.py``
— invisible to the registry, so the live telemetry could report time
but never "fraction of what the silicon could do".  This module is the
one source of truth: ``bench.py`` imports its constants from here, and
the scrape-time MFU join (:mod:`.xlacost`) resolves the running
backend's spec through :func:`spec_for_platform`.

Unknown backends (the CPU tests run on, or a TPU generation not in the
table) resolve to ``None``: cost capture still exports the program's
flops / bytes / arithmetic intensity — those are computation-intrinsic
— but no utilization gauge is derived, because a made-up peak would be
worse than none.  :func:`set_override` lets a deployment (or a test)
pin the spec explicitly, e.g. when modeling v5e numbers from a CPU dry
run the way ``bench.py`` always has.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Dict, Optional


@dataclasses.dataclass(frozen=True)
class HwSpec:
    """Public peak figures of one accelerator generation."""

    name: str
    peak_flops: float        #: dense bf16 peak, FLOP/s per chip
    hbm_bw: float            #: HBM bandwidth, bytes/s per chip
    ici_bw: float = 0.0      #: aggregate ICI, bytes/s per chip
    chip_hour_usd: float = 0.0  #: on-demand list price, $/chip-hour

    @property
    def ridge(self) -> float:
        """Roofline ridge point (flops/byte): programs above it are
        compute-bound, below it bandwidth-bound."""
        return self.peak_flops / self.hbm_bw if self.hbm_bw else 0.0


#: v5e public spec — the numbers every bench figure has been quoted
#: against since the first roofline block (197 TFLOP/s bf16, 819 GB/s
#: HBM, 1,600 Gbps/chip aggregate ICI, $1.20/chip-hour on-demand list)
V5E = HwSpec(name="tpu-v5e", peak_flops=197e12, hbm_bw=819e9,
             ici_bw=200e9, chip_hour_usd=1.20)

#: bench.py compatibility constants (satellite: one source of truth —
#: the bench imports these instead of carrying its own copies)
V5E_BF16_PEAK = V5E.peak_flops
V5E_HBM_BW = V5E.hbm_bw
V5E_ICI_BYTES_PER_S = V5E.ici_bw

#: platform tag (``jax.Device.platform``) -> spec.  TPU resolves to the
#: v5e figures (the paper's target part); CPU and anything unknown maps
#: to None — intensity-only reporting (see module docstring).
PLATFORM_SPECS: Dict[str, Optional[HwSpec]] = {
    "tpu": V5E,
    "cpu": None,
}

_lock = threading.Lock()
_override: Optional[HwSpec] = None


def set_override(spec: Optional[HwSpec]) -> Optional[HwSpec]:
    """Pin the spec every utilization derivation uses (None clears it).
    Returns the previous override so tests can restore it."""
    global _override
    with _lock:
        prev = _override
        _override = spec
    return prev


def spec_for_platform(platform: Optional[str]) -> Optional[HwSpec]:
    """The peak table entry for a backend platform tag, or None when
    the hardware is unknown (utilization must not be derived)."""
    with _lock:
        if _override is not None:
            return _override
    return PLATFORM_SPECS.get(str(platform or "").lower())


def chip_hour_price(platform: Optional[str] = None) -> float:
    """The $/chip-hour figure the tenant cost export multiplies
    device-seconds by (``nns_tenant_dollars_total``).  Resolution
    order: ``NNS_TPU_CHIP_HOUR_USD`` (deployment override — negotiated
    pricing differs from list), then the active spec override, then the
    platform table.  0.0 when the hardware (and hence a price) is
    unknown — a dollars figure from a made-up price would be worse
    than none; the tenant table still carries device-seconds."""
    env = os.environ.get("NNS_TPU_CHIP_HOUR_USD", "").strip()
    if env:
        try:
            return max(float(env), 0.0)
        except ValueError:
            pass  # a malformed override must not break a scrape
    spec = spec_for_platform(platform)
    if spec is None and platform is None:
        # no platform named: price against the default part (the same
        # v5e-by-default stance the bench's roofline figures take)
        spec = V5E
    return spec.chip_hour_usd if spec is not None else 0.0

"""Test harness config: run on CPU with 8 virtual devices so multi-chip
sharding paths are exercised without TPU hardware (the driver separately
dry-runs the multichip path; bench.py runs on the real chip)."""

import os
import sys

# Must be set before jax initializes its backend.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# In-tree convenience only: an installed nnstreamer_tpu wins, so the
# suite also validates `pip install .` copies (run pytest from anywhere).
try:
    import nnstreamer_tpu  # noqa: F401
except ImportError:
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))

"""Bitmap-font text overlay for decoder video output.

Parity target: /root/reference/ext/nnstreamer/tensor_decoder/
tensordec-font.c (8×13 raster font) + ``draw_label`` users in
tensordec-boundingbox.cc and tensordec-pose.c:635-661, which stamp label
text into the RGBA overlay frame.

TPU-native notes: glyphs are rasterized once per process with PIL's
built-in bitmap font into a boolean mask cache; drawing is a vectorized
numpy masked assignment on the host-side overlay frame (the overlay is a
presentation artifact — it never rides the XLA path).
"""

from __future__ import annotations

import threading
from typing import Dict, Sequence, Tuple

import numpy as np

_lock = threading.Lock()
_glyphs: Dict[str, np.ndarray] = {}
GLYPH_H = 13  # match the reference's 13-row raster height


def _rasterize(ch: str) -> np.ndarray:
    """Boolean (GLYPH_H, w) mask for one character."""
    try:
        from PIL import Image, ImageDraw, ImageFont

        font = ImageFont.load_default()
        l, t, r, b = font.getbbox(ch)
        w = max(r, 1)
        img = Image.new("L", (w, GLYPH_H), 0)
        ImageDraw.Draw(img).text((0, 0), ch, fill=255, font=font)
        return np.asarray(img) > 127
    except Exception:
        # PIL-less fallback: fixed-width filled block so layout survives
        m = np.zeros((GLYPH_H, 8), bool)
        if not ch.isspace():
            m[2:11, 1:7] = True
        return m


def glyph(ch: str) -> np.ndarray:
    with _lock:
        g = _glyphs.get(ch)
        if g is None:
            g = _glyphs[ch] = _rasterize(ch)
        return g


def text_mask(text: str) -> np.ndarray:
    """Boolean (GLYPH_H, total_w) mask for a string."""
    if not text:
        return np.zeros((GLYPH_H, 0), bool)
    parts = [glyph(c) for c in text]
    return np.concatenate(parts, axis=1)


def draw_text(frame: np.ndarray, x: int, y: int, text: str,
              color: Sequence[int] = (0, 255, 0, 255)) -> None:
    """Stamp ``text`` into an (H, W, C) uint8 frame at (x, y), clipped.

    Mirrors the reference draw_label semantics: the label is drawn above
    the given anchor when it fits, pixels outside the frame are dropped.
    """
    h, w = frame.shape[:2]
    mask = text_mask(text)
    mh, mw = mask.shape
    if mh == 0 or mw == 0:
        return
    x0, y0 = max(int(x), 0), max(int(y), 0)
    x1, y1 = min(int(x) + mw, w), min(int(y) + mh, h)
    if x0 >= x1 or y0 >= y1:
        return
    sub = mask[y0 - int(y):y1 - int(y), x0 - int(x):x1 - int(x)]
    c = np.asarray(color[:frame.shape[2]], np.uint8)
    frame[y0:y1, x0:x1][sub] = c


def label_anchor(box_x: int, box_y: int) -> Tuple[int, int]:
    """Place a label just above a box corner (reference behavior), or at
    the corner when the box touches the top edge."""
    y = box_y - GLYPH_H - 1
    return box_x, (y if y >= 0 else box_y + 1)

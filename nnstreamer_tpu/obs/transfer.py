"""Host↔device transfer ledger — byte-exact data-movement accounting.

PR 7 attributed dispatch *time* (host-prep / device / host-drain) but
not *movement*: nothing could say where bytes cross the host/device
boundary or how many crossings a frame pays, even though the composite
bench's latency floor is host-roundtrip-dominated (ROADMAP item 3).
This module is the measurement substrate the device-resident-dataflow
rework will be judged against.

Every host→device and device→host crossing at the jax seams records
into the process-wide :data:`LEDGER`:

- ``Tensor.jax()`` uploads and ``Tensor.np()`` drains (core/buffer.py)
  — the residency conversions the pipeline hot path actually performs;
- explicit ``device_put`` placement of inputs (filters/jax_xla.py
  ``invoke``/``invoke_batched``) and of weights (``ModelDef.flat_fn`` /
  ``mesh_fn``);
- micro-batch window feeds: host arrays handed to the batched
  executable (transferred by XLA's own arg handling — counted at the
  feed site with zero duration) and the pad-slot replays.

Rows are keyed ``(pipeline, source, direction, reason)`` with
``direction`` ``h2d``/``d2h``/``d2d`` and ``reason`` one of
``input``/``weights``/``drain``/``pad``/``handoff``.  ``d2d`` rows are
device→device moves (the cross-stage HBM handoff of a pipeline split
over disjoint device subsets): they never touch the host, so the
crossings-per-frame accounting (which counts host↔device residency
flips) stays at 0.0 while the handoff bytes remain byte-exact on the
ledger.  The *labels* come from a
thread-local context the runtime pushes around each element chain
(``runtime/element.py``), micro-batch flush and pool dispatch — the
recording site itself only knows the bytes.  Counts and bytes are
EXACT (``nbytes`` of the crossing array, every crossing counted, no
sampling); durations feed a per-row histogram.

Exported by the metrics registry at scrape time like every other
collected stat: ``nns_transfer_bytes_total`` /
``nns_transfer_count_total`` counters and ``nns_transfer_seconds``
histograms, the snapshot's ``transfers`` table (v4), XFER B/s and
X/FRAME columns in ``nns-top``, and — for sampled buffers — Chrome
trace ``xfer`` sub-spans via the trace dicts the context carries.

The whole subsystem obeys the global observability kill switch
(``NNS_TPU_OBS_DISABLE``, :func:`nnstreamer_tpu.obs.hooks.obs_disabled`)
and can be toggled programmatically with :func:`set_enabled` — the
on/off A/B the transfer bench gates the <3% overhead claim with.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from typing import Any, Dict, List, Optional, Tuple

from . import hooks as _hooks

#: crossing directions and reasons (the label vocabulary); ``d2d`` is
#: the cross-stage HBM handoff (never a host crossing), ``handoff``
#: its reason tag
DIRECTIONS = ("h2d", "d2h", "d2d")
REASONS = ("input", "weights", "drain", "pad", "handoff")

#: transfer duration histogram bounds (seconds): sub-µs CPU-backend
#: no-op conversions up to multi-second tunneled weight placements
TRANSFER_SECONDS_BUCKETS = (1e-6, 2.5e-6, 5e-6, 1e-5, 2.5e-5, 5e-5,
                            1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3,
                            .01, .025, .05, .1, .25, 1.0, float("inf"))

#: fast-path flag every recording site reads first (one attribute load
#: + branch, same cost class as the tracer hook); honors the global
#: obs kill switch at process start
ACTIVE = not _hooks.DISABLED


def set_enabled(flag: bool) -> None:
    """Programmatic on/off (bench A/B, tests).  The env kill switch
    (``NNS_TPU_OBS_DISABLE``) wins: it cannot be re-enabled at
    runtime — the hot paths were told at startup the whole obs layer
    is off."""
    global ACTIVE
    ACTIVE = bool(flag) and not _hooks.DISABLED


class _Row:
    """One (pipeline, source, direction, reason) series: exact count
    and bytes plus a duration histogram (guarded by the ledger lock)."""

    __slots__ = ("count", "bytes", "seconds", "buckets")

    def __init__(self):
        self.count = 0
        self.bytes = 0
        self.seconds = 0.0
        self.buckets = [0] * len(TRANSFER_SECONDS_BUCKETS)


class TransferLedger:
    """Process-wide, thread-safe table of host↔device crossings."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rows: Dict[Tuple[str, str, str, str], _Row] = {}

    def record(self, direction: str, reason: str, nbytes: int,
               seconds: float = 0.0, source: Optional[str] = None,
               pipeline: Optional[str] = None) -> None:
        """Count one crossing.  ``source``/``pipeline`` default to the
        thread-local context the runtime pushed (empty outside any
        element).  ``seconds=0`` marks a transfer performed inside the
        executable's own arg handling (counted, not separately
        timed)."""
        ctx = getattr(_TLS, "ctx", None)
        if pipeline is None:
            pipeline = ctx[0] if ctx is not None else ""
        if source is None:
            source = ctx[1] if ctx is not None else ""
        key = (pipeline, source, direction, reason)
        with self._lock:
            row = self._rows.get(key)
            if row is None:
                row = self._rows[key] = _Row()
            row.count += 1
            row.bytes += nbytes
            row.seconds += seconds
            row.buckets[bisect_left(TRANSFER_SECONDS_BUCKETS,
                                    seconds)] += 1
        if ctx is not None and ctx[2]:
            # sampled buffers in flight: the crossing renders as a
            # Chrome-trace `xfer` sub-span inside the owning element's
            # residency span (obs/tracer.py chrome_trace)
            t_end = time.monotonic()
            span = (t_end - float(seconds), float(seconds), str(source),
                    direction, reason, int(nbytes))
            for tr in ctx[2]:
                tr.setdefault("xfers", []).append(span)

    # -- pull side -----------------------------------------------------------

    def snapshot(self) -> List[dict]:
        """Rows for the registry's ``transfers`` table (v4), sorted."""
        with self._lock:
            return [{"pipeline": pl, "source": src, "direction": d,
                     "reason": r, "count": row.count,
                     "bytes": row.bytes, "seconds": row.seconds,
                     "buckets": list(row.buckets)}
                    for (pl, src, d, r), row
                    in sorted(self._rows.items())]

    def totals(self, pipeline: Optional[str] = None,
               direction: Optional[str] = None,
               reason: Optional[str] = None) -> Tuple[int, int]:
        """(count, bytes) summed over rows matching the given labels —
        the bench/test accounting helper."""
        count = nbytes = 0
        with self._lock:
            for (pl, _src, d, r), row in self._rows.items():
                if pipeline is not None and pl != pipeline:
                    continue
                if direction is not None and d != direction:
                    continue
                if reason is not None and r != reason:
                    continue
                count += row.count
                nbytes += row.bytes
        return count, nbytes

    def clear(self) -> None:
        """Tests/bench only: drop every row."""
        with self._lock:
            self._rows.clear()


#: the process-wide ledger every recording seam feeds
LEDGER = TransferLedger()

_TLS = threading.local()


def push_context(pipeline: str, source: str,
                 traces: Optional[tuple] = None):
    """Install the transfer-label context for the current thread
    (returns the previous context for :func:`pop_context`).  ``traces``
    optionally carries the trace dicts of sampled buffers in flight so
    crossings render as Chrome-trace sub-spans."""
    prev = getattr(_TLS, "ctx", None)
    _TLS.ctx = (pipeline, source, traces)
    return prev


def pop_context(prev) -> None:
    _TLS.ctx = prev


def record(direction: str, reason: str, nbytes: int,
           seconds: float = 0.0, source: Optional[str] = None,
           pipeline: Optional[str] = None) -> None:
    """Module-level recording shim: no-op unless :data:`ACTIVE`."""
    if not ACTIVE:
        return
    LEDGER.record(direction, reason, nbytes, seconds,
                  source=source, pipeline=pipeline)


def params_nbytes(params: Any) -> int:
    """Total payload bytes of a weight pytree (host or device leaves)."""
    try:
        from jax.tree_util import tree_leaves
    except ImportError:  # pragma: no cover - jax always present here
        return 0
    return sum(int(getattr(leaf, "nbytes", 0))
               for leaf in tree_leaves(params))

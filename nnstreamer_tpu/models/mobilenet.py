"""MobileNetV1 / MobileNetV2 in pure JAX, TPU-first.

Capability parity: the classification model files the reference feeds its
filter sub-plugins (``mobilenet_v1_1.0_224_quant.tflite``,
``mobilenet_v2_1.0_224_quant.tflite`` — /root/reference/tests/
nnstreamer_filter_tensorflow2_lite/runTest.sh), here as jittable functions.

TPU design notes:
- NHWC layout end-to-end; convs lower to MXU via
  ``lax.conv_general_dilated`` with ``('NHWC','HWIO','NHWC')``.
- Compute dtype defaults to bfloat16 (MXU-native); params stay float32 and
  cast at apply time so one param pytree serves train and serve paths.
- Inference applies *folded* batch-norm (scale/bias precomputed into the
  conv epilogue) so the whole block fuses into one XLA computation; train
  mode uses batch statistics.
- No Python control flow on data — a fixed block list unrolls at trace time.

Params are nested dicts (pytrees): serialization-friendly and directly
shardable with jax.sharding NamedSharding annotations.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

Params = Dict[str, Any]

_DN = ("NHWC", "HWIO", "NHWC")
_BN_EPS = 1e-3


def _rng_of(key) -> np.random.Generator:
    """Host-side init RNG.  Accepts an int seed or a jax PRNGKey (its raw
    data seeds numpy).  Init runs on host with zero XLA compiles — params
    only move to device when first used under jit."""
    if isinstance(key, np.random.Generator):
        return key
    if hasattr(key, "dtype"):  # PRNGKey (old-style uint32 pair or new-style)
        try:
            import jax

            key = int(np.asarray(jax.random.key_data(key)).ravel()[-1])
        except Exception:  # noqa: BLE001 - any key layout
            key = int(np.asarray(key).ravel()[-1])
    return np.random.default_rng(int(key))


# -- primitive layers --------------------------------------------------------


def _conv_init(rng: np.random.Generator, kh, kw, cin, cout,
               groups: int = 1) -> Params:
    fan_in = kh * kw * cin // groups
    w = np.clip(rng.standard_normal(
        (kh, kw, cin // groups, cout), dtype=np.float32), -2, 2)
    w = w * np.sqrt(2.0 / max(fan_in, 1), dtype=np.float32)
    return {
        "w": w,
        # batch-norm params (fused at inference)
        "scale": np.ones((cout,), np.float32),
        "bias": np.zeros((cout,), np.float32),
        "mean": np.zeros((cout,), np.float32),
        "var": np.ones((cout,), np.float32),
    }


def _conv_bn(p: Params, x, stride: int, groups: int = 1, relu6: bool = True,
             train: bool = False, dtype=jnp.bfloat16):
    w = p["w"].astype(dtype)
    y = lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding="SAME",
        dimension_numbers=_DN, feature_group_count=groups)
    if train:
        mean = jnp.mean(y.astype(jnp.float32), axis=(0, 1, 2))
        var = jnp.var(y.astype(jnp.float32), axis=(0, 1, 2))
    else:
        mean, var = p["mean"], p["var"]
    inv = (p["scale"] * lax.rsqrt(var + _BN_EPS)).astype(dtype)
    off = (p["bias"] - mean * p["scale"] * lax.rsqrt(var + _BN_EPS)).astype(dtype)
    y = y * inv + off
    if relu6:
        y = jnp.clip(y, 0.0, 6.0)
    return y


def _dense_init(rng: np.random.Generator, cin, cout) -> Params:
    w = np.clip(rng.standard_normal((cin, cout), dtype=np.float32), -2, 2)
    return {"w": w * np.sqrt(1.0 / cin, dtype=np.float32),
            "b": np.zeros((cout,), np.float32)}


def _dense(p: Params, x, dtype=jnp.bfloat16):
    return x @ p["w"].astype(dtype) + p["b"].astype(dtype)


# -- MobileNetV1 -------------------------------------------------------------

# (stride, out_channels) per depthwise-separable block.
_V1_BLOCKS: List[Tuple[int, int]] = [
    (1, 64), (2, 128), (1, 128), (2, 256), (1, 256), (2, 512),
    (1, 512), (1, 512), (1, 512), (1, 512), (1, 512),
    (2, 1024), (1, 1024),
]


def mobilenet_v1_init(key, num_classes: int = 1001,
                      width: float = 1.0) -> Params:
    def ch(c):
        return max(8, int(c * width))

    rng = _rng_of(key)
    params: Params = {"stem": _conv_init(rng, 3, 3, 3, ch(32))}
    cin = ch(32)
    blocks = []
    for stride, cout in _V1_BLOCKS:
        cout = ch(cout)
        blocks.append({
            "dw": _conv_init(rng, 3, 3, cin, cin, groups=cin),
            "pw": _conv_init(rng, 1, 1, cin, cout),
        })
        cin = cout
    params["blocks"] = blocks
    params["head"] = _dense_init(rng, cin, num_classes)
    return params


def mobilenet_v1_apply(params: Params, x, train: bool = False,
                       dtype=jnp.bfloat16):
    """``x``: NHWC float in [0,1] or normalized; returns (N, num_classes)
    logits in float32."""
    x = x.astype(dtype)
    x = _conv_bn(params["stem"], x, stride=2, train=train, dtype=dtype)
    for i, (stride, _cout) in enumerate(_V1_BLOCKS):
        b = params["blocks"][i]
        cin = b["dw"]["w"].shape[3]
        x = _conv_bn(b["dw"], x, stride=stride, groups=cin, train=train,
                     dtype=dtype)
        x = _conv_bn(b["pw"], x, stride=1, train=train, dtype=dtype)
    x = jnp.mean(x, axis=(1, 2))  # global average pool
    return _dense(params["head"], x, dtype=dtype).astype(jnp.float32)


# -- MobileNetV2 -------------------------------------------------------------

# (expansion, out_channels, num_repeats, first_stride)
_V2_BLOCKS: List[Tuple[int, int, int, int]] = [
    (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
    (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
]


def _inverted_residual_init(rng: np.random.Generator, cin, cout,
                            expansion) -> Params:
    mid = cin * expansion
    p: Params = {}
    if expansion != 1:
        p["expand"] = _conv_init(rng, 1, 1, cin, mid)
    p["dw"] = _conv_init(rng, 3, 3, mid, mid, groups=mid)
    p["project"] = _conv_init(rng, 1, 1, mid, cout)
    return p


def _inverted_residual(p: Params, x, stride: int, train: bool, dtype):
    h = x
    if "expand" in p:
        h = _conv_bn(p["expand"], h, stride=1, train=train, dtype=dtype)
    mid = h.shape[-1]
    h = _conv_bn(p["dw"], h, stride=stride, groups=mid, train=train,
                 dtype=dtype)
    h = _conv_bn(p["project"], h, stride=1, relu6=False, train=train,
                 dtype=dtype)
    if stride == 1 and x.shape[-1] == h.shape[-1]:
        h = h + x  # residual
    return h


def mobilenet_v2_init(key, num_classes: int = 1001,
                      width: float = 1.0) -> Params:
    def ch(c):
        return max(8, int(c * width))

    rng = _rng_of(key)
    params: Params = {"stem": _conv_init(rng, 3, 3, 3, ch(32))}
    cin = ch(32)
    blocks = []
    for t, c, n, s in _V2_BLOCKS:
        for _ in range(n):
            blocks.append(_inverted_residual_init(rng, cin, ch(c), t))
            cin = ch(c)
    params["blocks"] = blocks
    last = max(1280, int(1280 * width))
    params["last"] = _conv_init(rng, 1, 1, cin, last)
    params["head"] = _dense_init(rng, last, num_classes)
    return params


def _v2_strides() -> List[int]:
    out = []
    for _t, _c, n, s in _V2_BLOCKS:
        out.extend([s] + [1] * (n - 1))
    return out


def mobilenet_v2_backbone(params: Params, x, train: bool = False,
                          dtype=jnp.bfloat16,
                          taps: Sequence[int] = ()) -> Tuple[Any, List[Any]]:
    """Run stem+blocks; returns (final feature map, [tapped feature maps]).

    ``taps`` are block indices whose *outputs* are collected — SSD heads
    attach at intermediate strides the way the reference's detection
    pipelines consume `ssd_mobilenet_v2` feature maps.
    """
    x = x.astype(dtype)
    x = _conv_bn(params["stem"], x, stride=2, train=train, dtype=dtype)
    tapped = []
    for i, stride in enumerate(_v2_strides()):
        x = _inverted_residual(params["blocks"][i], x, stride, train, dtype)
        if i in taps:
            tapped.append(x)
    return x, tapped


def mobilenet_v2_apply(params: Params, x, train: bool = False,
                       dtype=jnp.bfloat16):
    x, _ = mobilenet_v2_backbone(params, x, train=train, dtype=dtype)
    x = _conv_bn(params["last"], x, stride=1, train=train, dtype=dtype)
    x = jnp.mean(x, axis=(1, 2))
    return _dense(params["head"], x, dtype=dtype).astype(jnp.float32)


# -- registration helpers ----------------------------------------------------


@functools.lru_cache(maxsize=None)
def _cached_params(family: str, num_classes: int, width: float, seed: int):
    key = jax.random.PRNGKey(seed)
    if family == "v1":
        return mobilenet_v1_init(key, num_classes, width)
    return mobilenet_v2_init(key, num_classes, width)


def register_mobilenet(name: str = "mobilenet_v1", family: str = "v1",
                       num_classes: int = 1001, width: float = 1.0,
                       batch: int = 1, size: int = 224, seed: int = 0) -> str:
    """Register a randomly-initialized MobileNet with the jax-xla filter
    (deterministic per seed — the framework's analog of the reference's tiny
    deterministic test models, usable at real benchmark scale)."""
    from ..filters.jax_xla import register_model

    params = _cached_params(family, num_classes, width, seed)
    apply = mobilenet_v1_apply if family == "v1" else mobilenet_v2_apply
    return register_model(
        name, apply, params=params,
        in_shapes=[(batch, size, size, 3)], in_dtypes=np.float32)

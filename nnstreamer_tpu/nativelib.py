"""Loader for the native (C++) runtime components.

The reference's core is native C/C++; this framework keeps the XLA
compute path in JAX and implements the host-side hot loops — currently
the L5 wire codec (``native/nns_wire.cc``) — in C++ behind a ctypes
C ABI, with the pure-Python implementations as transparent fallback.

Build: ``make -C native`` (g++, no third-party deps).  The loader also
self-builds on first use when a toolchain is present; set
``NNS_TPU_NO_NATIVE=1`` to force the Python fallback.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_lock = threading.Lock()
_lib = None
_tried = False

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "native")
_SO = os.path.join(_NATIVE_DIR, "build", "libnns_tpu_native.so")

RANK_LIMIT = 16


def _configure(lib) -> None:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.nns_pb_encode_bound.restype = ctypes.c_uint64
    lib.nns_pb_encode_bound.argtypes = [
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint32),
        ctypes.c_uint32]
    lib.nns_pb_encode.restype = ctypes.c_uint64
    lib.nns_pb_encode.argtypes = [
        ctypes.POINTER(u8p), ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_uint32),
        ctypes.POINTER(u8p), ctypes.POINTER(ctypes.c_uint32),
        ctypes.c_uint32, ctypes.c_int32, ctypes.c_int32, ctypes.c_uint32,
        u8p, ctypes.c_uint64]
    lib.nns_pb_decode.restype = ctypes.c_int32
    lib.nns_pb_decode.argtypes = [
        u8p, ctypes.c_uint64, ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_uint32),
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_uint32)]


def _build() -> bool:
    """Build in-tree; for installed (possibly read-only) copies, fall back
    to a per-user cache directory and point the loader there."""
    global _SO
    try:
        r = subprocess.run(["make", "-C", _NATIVE_DIR], capture_output=True,
                           timeout=120)
        if r.returncode == 0 and os.path.isfile(_SO):
            return True
    except (OSError, subprocess.TimeoutExpired):
        return False
    cache = os.path.join(
        os.environ.get("XDG_CACHE_HOME",
                       os.path.join(os.path.expanduser("~"), ".cache")),
        "nnstreamer_tpu", "native")
    so = os.path.join(cache, "libnns_tpu_native.so")
    try:
        os.makedirs(cache, exist_ok=True)
        r = subprocess.run(
            ["make", "-C", _NATIVE_DIR, f"BUILD={cache}", f"LIB={so}"],
            capture_output=True, timeout=120)
        if r.returncode == 0 and os.path.isfile(so):
            _SO = so
            return True
    except (OSError, subprocess.TimeoutExpired):
        pass
    return False


def get_native() -> Optional[ctypes.CDLL]:
    """The native library, building it on first use; None = fallback."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("NNS_TPU_NO_NATIVE"):
            return None
        src = os.path.join(_NATIVE_DIR, "nns_wire.cc")
        stale = not os.path.isfile(_SO) or (
            os.path.isfile(src)
            and os.path.getmtime(src) > os.path.getmtime(_SO))
        if stale and not _build() and not os.path.isfile(_SO):
            return None
        try:
            lib = ctypes.CDLL(_SO)
            _configure(lib)
            _lib = lib
        except OSError:
            _lib = None
    return _lib

"""Process-wide chaos hook: the one global the hot paths read.

Mirrors :mod:`nnstreamer_tpu.obs.hooks` — seams (edge transports, the
serving dispatch, the batching window) read ``plan`` ONCE per event and
do nothing when it is ``None``, so an un-chaosed process pays a single
attribute load per frame.  Install a plan with
:func:`nnstreamer_tpu.chaos.install_plan` (or the ``NNS_TPU_CHAOS``
environment variable, picked up when the first pipeline starts).
"""

from __future__ import annotations

import os
from typing import Optional

#: the active FaultPlan, or None (chaos detached — the default)
plan = None

_env_checked = False


def maybe_install_from_env() -> None:
    """``NNS_TPU_CHAOS=<spec>`` installs a process-wide plan when the
    first pipeline starts (same activation hook as the metrics
    endpoint's ``NNS_TPU_METRICS_PORT``).  Checked once per process."""
    global _env_checked, plan
    if _env_checked:
        return
    _env_checked = True
    spec = os.environ.get("NNS_TPU_CHAOS", "").strip()
    if not spec or plan is not None:
        return
    from .plan import FaultPlan

    try:
        plan = FaultPlan.parse(spec)
    except ValueError as e:
        from ..utils.log import logw

        logw("ignoring malformed NNS_TPU_CHAOS=%r: %s", spec, e)


def active_plan() -> Optional["object"]:
    return plan

"""True cross-PROCESS offload: a query server pipeline in a spawned
python subprocess, the client in this process, over localhost TCP —
the reference's paired-gst-launch-processes SSAT shape
(/root/reference/tests/nnstreamer_edge/query/runTest.sh).
"""

import os
import signal
import subprocess
import sys
import textwrap
import time
from fractions import Fraction

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.elements.basic import AppSink, AppSrc
from nnstreamer_tpu.runtime import Pipeline
from nnstreamer_tpu.runtime.registry import make

SERVER_SCRIPT = textwrap.dedent("""\
    import sys
    sys.path.insert(0, {repo!r})
    import numpy as np
    from nnstreamer_tpu.core import TensorsSpec
    from nnstreamer_tpu.filters.custom import register_custom_easy
    from nnstreamer_tpu.runtime import Pipeline
    from nnstreamer_tpu.runtime.registry import make

    spec = TensorsSpec.parse("4:1", "float32")
    register_custom_easy("xp_triple", lambda xs: [xs[0] * 3.0],
                         in_spec=spec, out_spec=spec)
    p = Pipeline(name="xp-server")
    src = make("tensor_query_serversrc", el_name="qsrc",
               connect_type="tcp", host="127.0.0.1", port=0, id=77)
    flt = make("tensor_filter", el_name="f", framework="custom-easy",
               model="xp_triple")
    snk = make("tensor_query_serversink", el_name="qsink", id=77)
    p.add(src, flt, snk).link(src, flt, snk)
    p.start()
    print(f"PORT={{src.port}}", flush=True)
    import time
    while True:
        time.sleep(0.2)
""")


@pytest.fixture
def server_proc(tmp_path):
    script = tmp_path / "server.py"
    script.write_text(SERVER_SCRIPT.format(
        repo=os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
    proc = subprocess.Popen([sys.executable, str(script)],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    port = None
    deadline = time.time() + 60
    while time.time() < deadline:
        line = proc.stdout.readline()
        if line.startswith("PORT="):
            port = int(line.strip().split("=", 1)[1])
            break
        if proc.poll() is not None:
            break
    if port is None:
        err = proc.stderr.read() if proc.poll() is not None else ""
        proc.kill()
        pytest.fail(f"server subprocess did not come up: {err[-800:]}")
    yield port
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=5)
    except subprocess.TimeoutExpired:
        proc.kill()


def test_offload_to_subprocess_server(server_proc):
    port = server_proc
    p = Pipeline(name="xp-client")
    src = AppSrc(name="src", spec=TensorsSpec.parse(
        "4:1", "float32", rate=Fraction(10)))
    cli = make("tensor_query_client", el_name="cli", host="127.0.0.1",
               port=port, connect_type="tcp", timeout=30000)
    snk = AppSink(name="out")
    p.add(src, cli, snk).link(src, cli, snk)
    with p:
        for i in range(4):
            src.push_buffer(Buffer.of(
                np.full((1, 4), float(i + 1), np.float32), pts=i))
        src.end_of_stream()
        assert p.wait_eos(timeout=60)
        got = []
        while True:
            b = snk.pull(timeout=0.5)
            if b is None:
                break
            got.append(b)
    assert len(got) == 4
    for i, b in enumerate(got):
        np.testing.assert_array_equal(
            b.tensors[0].np(), np.full((1, 4), 3.0 * (i + 1), np.float32))

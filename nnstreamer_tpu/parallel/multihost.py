"""Multi-host distributed runtime: process init + hybrid ICI/DCN meshes.

Parity target: the reference's cross-host communication backend — the
external nnstreamer-edge library plus MQTT/gRPC bridges (SURVEY.md §5.8)
— whose TPU-native form is the XLA runtime itself: every host runs the
same program, `jax.distributed` forms the process group, and collectives
ride ICI within a slice and DCN across slices.  Pipelines then scale
multi-host with NO element changes: the jax-xla filter's computation is
jitted over a global mesh and XLA inserts the cross-host collectives
(the "pick a mesh → annotate shardings → let XLA place collectives"
recipe).

- :func:`initialize` wraps ``jax.distributed.initialize`` with
  environment autodetection (TPU pods populate coordinator/process info
  themselves; explicit args serve CPU/GPU clusters and tests).
- :func:`hybrid_mesh` builds a Mesh whose outer axes span hosts over DCN
  and inner axes span the ICI-connected devices of each slice — the
  layout that keeps bandwidth-hungry collectives (tensor/sequence
  parallel) on ICI and only data-parallel gradient reductions on DCN.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join (or form) the multi-host process group.

    On TPU pods all arguments are autodetected from the runtime
    environment; pass them explicitly for CPU/GPU clusters.  Safe to call
    once per process, before any other jax API touches the backend.
    """
    import jax

    kw = {}
    if coordinator_address is not None:
        kw["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kw["num_processes"] = num_processes
    if process_id is not None:
        kw["process_id"] = process_id
    jax.distributed.initialize(**kw)


def process_info() -> Tuple[int, int]:
    """(process_index, process_count) of this host."""
    import jax

    return jax.process_index(), jax.process_count()


def hybrid_mesh(ici_axes: Sequence[Tuple[str, int]],
                dcn_axes: Optional[Sequence[Tuple[str, int]]] = None,
                devices=None):
    """Mesh with DCN-spanning outer axes and ICI-spanning inner axes.

    ``ici_axes``: (name, size) per intra-slice axis, e.g.
    ``[("model", 4), ("data", 2)]``.  ``dcn_axes``: (name, size) per
    cross-host axis, e.g. ``[("replica", num_slices)]``; defaults to a
    size-1 ``replica`` axis so single-slice runs use the same call.
    """
    import jax
    from jax.experimental import mesh_utils

    dcn_axes = list(dcn_axes or [("replica", 1)])
    ici_axes = list(ici_axes)
    names = tuple(n for n, _ in dcn_axes) + tuple(n for n, _ in ici_axes)
    ici_shape = tuple(s for _, s in ici_axes)
    dcn_shape = tuple(s for _, s in dcn_axes)
    if all(s == 1 for s in dcn_shape):
        # single-slice: a plain device mesh with leading unit axes keeps
        # the axis names (and therefore the sharding annotations) stable
        devs = devices if devices is not None else jax.devices()
        import numpy as np

        n = int(np.prod(ici_shape))
        if len(devs) < n:
            raise ValueError(
                f"hybrid_mesh: need {n} devices for {ici_axes}, have "
                f"{len(devs)}")
        arr = np.array(devs[:n]).reshape(dcn_shape + ici_shape)
        return jax.sharding.Mesh(arr, names)
    # create_hybrid_device_mesh multiplies mesh and dcn shapes
    # ELEMENTWISE (np.block), so both must be padded to the combined
    # rank: DCN axes lead with unit ICI extents and vice versa.
    mesh_shape = (1,) * len(dcn_shape) + ici_shape
    dcn_mesh_shape = dcn_shape + (1,) * len(ici_shape)
    try:
        arr = mesh_utils.create_hybrid_device_mesh(
            mesh_shape, dcn_mesh_shape, devices=devices)
    except (ValueError, AttributeError):
        # non-TPU process groups (CPU/GPU clusters) carry no
        # slice_index — mesh_utils either sees one big slice
        # (ValueError) or trips on the missing attribute entirely
        # (AttributeError, backend-dependent): group by process_index
        # instead — DCN axes span processes, ICI axes span each
        # process's local devices
        arr = _mesh_by_process(jax, devices, dcn_shape, ici_shape)
    return jax.sharding.Mesh(arr, names)


def _mesh_by_process(jax, devices, dcn_shape, ici_shape):
    import numpy as np

    devs = list(devices) if devices is not None else jax.devices()
    groups: dict = {}
    for d in devs:
        groups.setdefault(d.process_index, []).append(d)
    ndcn = int(np.prod(dcn_shape))
    nici = int(np.prod(ici_shape))
    if len(groups) != ndcn:
        raise ValueError(
            f"hybrid_mesh: dcn axes {tuple(dcn_shape)} want {ndcn} "
            f"processes, group has {len(groups)}")
    ordered = []
    for pi in sorted(groups):
        local = sorted(groups[pi], key=lambda d: d.id)
        if len(local) < nici:
            raise ValueError(
                f"hybrid_mesh: ici axes {tuple(ici_shape)} want {nici} "
                f"devices per process, process {pi} has {len(local)}")
        ordered.extend(local[:nici])
    return np.array(ordered).reshape(tuple(dcn_shape) + tuple(ici_shape))

"""Disaggregated pipeline-split serving (stage placement over the
device channel + conditional cascade offload).

Covers the ISSUE-18 acceptance surface: split-vs-fused parity
frame-for-frame through the REAL cascade element path (device_src →
detector → tensor_crop → tensor_if offload=then → classifier),
crossings staying at exactly 0.0 across the stage boundary with a
byte-exact ``d2d``/``handoff`` transfer-ledger row, tensor_if
FIFO/pts integrity under concurrent streams with mixed offload
decisions, and the PR-10/11-style race harness on stage-pool
start/stop churn.
"""

import threading

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.elements.basic import AppSink, AppSrc, Queue
from nnstreamer_tpu.elements.condition import TensorIf
from nnstreamer_tpu.elements.crop import TensorCrop
from nnstreamer_tpu.elements.devicesrc import DeviceSrc
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.filters.jax_xla import (
    JaxXlaFilter,
    register_model,
    unregister_model,
)
from nnstreamer_tpu.obs.stagestat import STAGE_STATS
from nnstreamer_tpu.obs.transfer import LEDGER
from nnstreamer_tpu.parallel.placement import reset_subsets
from nnstreamer_tpu.runtime import MODEL_POOL, Pipeline

jax = pytest.importorskip("jax")

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8,
    reason="stage split needs the 8-chip (virtual) inventory")

SHAPE = (8, 8, 3)
CROP = (6, 6)                       # fixed region at (0,0)
CROP_SHAPE = (CROP[0], CROP[1], SHAPE[2])
CROP_BYTES = CROP[0] * CROP[1] * SHAPE[2] * 4
PERIOD = 4                          # frame values cycle 0..3
THRESHOLD = 3.0                     # det adds 1: {2,3} offload — half


@pytest.fixture(scope="module", autouse=True)
def _models():
    register_model("_t_stage_det", lambda x: x + 1.0,
                   in_shapes=[SHAPE], in_dtypes=np.float32)
    register_model("_t_stage_cls",
                   lambda x: (x * 2.0 + 1.0).sum(axis=(0, 1)),
                   in_shapes=[CROP_SHAPE], in_dtypes=np.float32)
    register_model("_t_stage_id", lambda x: x * 1.0,
                   in_shapes=[CROP_SHAPE], in_dtypes=np.float32)
    yield
    for n in ("_t_stage_det", "_t_stage_cls", "_t_stage_id"):
        unregister_model(n)


@pytest.fixture(autouse=True)
def _clean():
    yield
    # a failed test must not leak pool refcounts, claimed subsets or
    # stage rows into the next one
    MODEL_POOL.clear()
    with JaxXlaFilter._shared_lock:
        JaxXlaFilter._shared_instances.clear()
    STAGE_STATS.reset()
    reset_subsets()


def _drain(sink):
    out = []
    while True:
        b = sink.pull(timeout=0.2)
        if b is None:
            return out
        out.append(b)


# -- the miniature cascade: bench.py's topology at test scale ----------------


def _cascade(tag, split, frames_n):
    """device_src (values cycling 0..3) → det (devices=0-3 when split)
    → tensor_crop → tensor_if (offload=then, ge 3.0) → cls
    (devices=4-7 when split) → off/keep sinks."""
    pname = f"stagesplit_{tag}"
    pool = [np.full(SHAPE, float(k), np.float32) for k in range(PERIOD)]
    p = Pipeline(name=pname)
    src = DeviceSrc(name="src", frames=pool, pool_size=PERIOD,
                    num_buffers=frames_n)
    info = AppSrc(name="regions",
                  spec=TensorsSpec.from_shapes([(1, 4)], np.uint32),
                  max_buffers=frames_n + 8)
    q1 = Queue(name="q1", max_size_buffers=64)
    det = TensorFilter(name="det", framework="jax-xla",
                       model="_t_stage_det", mesh="data:4",
                       devices="0-3" if split else "", batch=4,
                       batch_buckets="4", batch_timeout_ms=20.0,
                       share_model=True, stat_sample_interval_ms=0)
    crop = TensorCrop(name="crop")
    route = TensorIf(name="route", compared_value="A_VALUE",
                     compared_value_option="0:0",
                     supplied_value=str(THRESHOLD), operator="ge",
                     offload="then", then="PASSTHROUGH",
                     else_="PASSTHROUGH")
    q2 = Queue(name="q2", max_size_buffers=64)
    cls = TensorFilter(name="cls", framework="jax-xla",
                       model="_t_stage_cls", mesh="data:4",
                       devices="4-7" if split else "", batch=4,
                       batch_buckets="4", batch_timeout_ms=20.0,
                       share_model=True, stat_sample_interval_ms=0)
    sink_off = AppSink(name="off", max_buffers=frames_n + 8)
    sink_keep = AppSink(name="keep", max_buffers=frames_n + 8)
    p.add(src, info, q1, det, crop, route, q2, cls, sink_off, sink_keep)
    p.link(src, q1, det)
    p.link_pads(det, "src", crop, "sink_raw")
    p.link_pads(info, "src", crop, "sink_info")
    p.link(crop, route)
    p.link_pads(route, "src_then", q2, "sink")
    p.link(q2, cls, sink_off)
    p.link_pads(route, "src_else", sink_keep, "sink")
    return p, info, sink_off, sink_keep, pname


def _feed(p, info, frames_n):
    region = np.array([[0, 0, CROP[1], CROP[0]]], np.uint32)
    p.start()
    for _ in range(frames_n):
        info.push_buffer(Buffer.of(region), timeout=60)
    info.end_of_stream()
    assert p.wait_eos(timeout=120), "cascade did not reach EOS"


def test_split_vs_fused_parity_frame_for_frame():
    """The split leg's outputs equal the fused leg's frame-for-frame —
    on BOTH branches — and match the analytic cascade exactly."""
    frames_n = 16
    outs = {}
    for tag, split in (("parity_split", True), ("parity_fused", False)):
        p, info, sink_off, sink_keep, _ = _cascade(tag, split, frames_n)
        try:
            _feed(p, info, frames_n)
            outs[tag] = (_drain(sink_off), _drain(sink_keep))
        finally:
            p.stop()
    off_s, keep_s = outs["parity_split"]
    off_f, keep_f = outs["parity_fused"]
    assert len(off_s) == len(off_f) == frames_n // 2
    assert len(keep_s) == len(keep_f) == frames_n // 2
    for a, b in zip(off_s + keep_s, off_f + keep_f):
        np.testing.assert_array_equal(a.tensors[0].np(), b.tensors[0].np())
    # analytic ground truth: values {2,3} offload, det adds 1, the
    # classifier sums (2v+1) over the 6x6 crop per channel — FIFO
    # order alternates 252, 324
    n = CROP[0] * CROP[1]
    want = [float((2 * (v + 1.0) + 1.0) * n) for v in (2.0, 3.0)]
    got = [float(b.tensors[0].np()[0]) for b in off_s]
    assert got == want * (frames_n // PERIOD)
    for i, b in enumerate(keep_s):  # kept frames: cropped det outs 1, 2
        np.testing.assert_array_equal(
            b.tensors[0].np(),
            np.full(CROP_SHAPE, float(i % 2 + 1.0), np.float32))


def test_split_crossings_zero_and_handoff_row_byte_exact():
    """The stage boundary never degrades to a drain/re-upload pair —
    crossings stay at exactly 0.0 — and the handoff leaves a
    byte-exact d2d ledger row plus a matching stage-stats row."""
    frames_n = 16
    p, info, sink_off, sink_keep, pname = _cascade("xzero", True, frames_n)
    x0 = LEDGER.totals(reason="input")[0] \
        + LEDGER.totals(reason="drain")[0]
    h0c, h0b = LEDGER.totals(direction="d2d", reason="handoff")
    try:
        _feed(p, info, frames_n)
        # measure BEFORE draining the sinks: pulling device-resident
        # frames to host np() records legitimate d2h drain rows
        x1 = LEDGER.totals(reason="input")[0] \
            + LEDGER.totals(reason="drain")[0]
        h1c, h1b = LEDGER.totals(direction="d2d", reason="handoff")
        assert x1 - x0 == 0, "stage handoff leaked a host crossing"
        assert h1c - h0c == frames_n // 2
        assert h1b - h0b == (frames_n // 2) * CROP_BYTES
        row = STAGE_STATS.get(pname, "cls")
        assert row is not None
        assert (row["from"], row["to"]) == ("0-3", "4-7")
        assert row["frames"] == frames_n // 2
        assert row["bytes"] == (frames_n // 2) * CROP_BYTES
        assert row["depth"] == 0, "inter-stage depth must drain to zero"
        orow = STAGE_STATS.get(pname, "route")
        assert orow["offloaded"] == frames_n // 2
        assert orow["kept"] == frames_n // 2
        assert orow["ratio"] == 0.5
        off, keep = _drain(sink_off), _drain(sink_keep)
        assert len(off) == len(keep) == frames_n // 2
    finally:
        p.stop()


def test_tensor_if_fifo_pts_concurrent_streams_mixed_offload():
    """Two concurrent streams route through tensor_if into ONE shared
    classifier pool on the 4-7 subset: per-stream FIFO order, pts and
    payload identity survive the mixed offload decisions."""
    frames_n = 24

    spec = TensorsSpec.from_shapes([CROP_SHAPE], np.float32)

    def _build(stream):
        p = Pipeline(name=f"stagesplit_if_{stream}")
        src = AppSrc(name="src", spec=spec, max_buffers=frames_n + 4)
        route = TensorIf(name="route", compared_value="A_VALUE",
                         compared_value_option="0:0",
                         supplied_value="2.0", operator="ge",
                         offload="then", then="PASSTHROUGH",
                         else_="PASSTHROUGH")
        q = Queue(name="q", max_size_buffers=frames_n + 4)
        cls = TensorFilter(name="cls", framework="jax-xla",
                           model="_t_stage_id", mesh="data:4",
                           devices="4-7", batch=4, batch_buckets="4",
                           batch_timeout_ms=20.0, share_model=True,
                           stat_sample_interval_ms=0)
        sink_off = AppSink(name="off", max_buffers=frames_n + 4)
        sink_keep = AppSink(name="keep", max_buffers=frames_n + 4)
        p.add(src, route, q, cls, sink_off, sink_keep)
        p.link(src, route)
        p.link_pads(route, "src_then", q, "sink")
        p.link(q, cls, sink_off)
        p.link_pads(route, "src_else", sink_keep, "sink")
        return p, src, sink_off, sink_keep

    def _frame(stream, i):
        # flat[0] routes (values {2,3} offload under ge 2.0); flat[1]
        # is a stream watermark so demux mixups are detectable, not
        # just ordering slips
        a = np.full(CROP_SHAPE, float(i % 4), np.float32)
        a.flat[1] = stream * 1000.0 + i
        return Buffer.of(a, pts=i)

    pipes = {s: _build(s) for s in (1, 2)}
    errors = []

    def pusher(stream):
        try:
            _, src, _, _ = pipes[stream]
            for i in range(frames_n):
                src.push_buffer(_frame(stream, i), timeout=60)
            src.end_of_stream()
        except Exception as exc:  # noqa: BLE001 - the assertion
            errors.append(exc)

    for p, *_ in pipes.values():
        p.start()
    try:
        threads = [threading.Thread(target=pusher, args=(s,))
                   for s in pipes]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors
        for p, *_ in pipes.values():
            assert p.wait_eos(timeout=120)
        exp_off = [i for i in range(frames_n) if i % 4 >= 2]
        exp_keep = [i for i in range(frames_n) if i % 4 < 2]
        for stream, (_, _, sink_off, sink_keep) in pipes.items():
            off, keep = _drain(sink_off), _drain(sink_keep)
            assert [b.pts for b in off] == exp_off
            assert [b.pts for b in keep] == exp_keep
            for b, i in zip(off, exp_off):
                assert float(b.tensors[0].np().flat[1]) \
                    == stream * 1000.0 + i
            for b, i in zip(keep, exp_keep):
                assert float(b.tensors[0].np().flat[1]) \
                    == stream * 1000.0 + i
    finally:
        for p, *_ in pipes.values():
            p.stop()


def test_stage_pool_start_stop_race_three_threads():
    """The PR-10/11 race harness on stage pools: 3 threads churning
    start/push/EOS/stop on the SAME staged subset while a keeper
    pipeline holds the pool entry alive — never a crash, never a lost
    frame."""
    spec = TensorsSpec.from_shapes([SHAPE], np.float32)

    def _stage_pipe(tag):
        p = Pipeline(name=f"stagesplit_race_{tag}")
        src = AppSrc(name="src", spec=spec, max_buffers=32)
        q = Queue(name="q", max_size_buffers=32)
        det = TensorFilter(name="det", framework="jax-xla",
                           model="_t_stage_det", mesh="data:4",
                           devices="0-3", batch=4, batch_buckets="4",
                           batch_timeout_ms=10.0, share_model=True,
                           stat_sample_interval_ms=0)
        sink = AppSink(name="sink", max_buffers=32)
        p.add(src, q, det, sink)
        p.link(src, q, det, sink)
        return p, src, sink

    rounds, per_round = 5, 4
    errors = []
    outcomes = {"frames": 0}
    lock = threading.Lock()

    def churn(tid):
        try:
            for r in range(rounds):
                p, src, sink = _stage_pipe(f"t{tid}_{r}")
                p.start()
                for i in range(per_round):
                    src.push_buffer(
                        Buffer.of(np.full(SHAPE, float(i), np.float32)),
                        timeout=30)
                src.end_of_stream()
                p.wait_eos(timeout=60, raise_on_error=False)
                got = len(_drain(sink))
                p.stop()
                with lock:
                    outcomes["frames"] += got
        except Exception as exc:  # noqa: BLE001 - the assertion
            errors.append(exc)

    # the keeper holds the staged pool entry (and its subset claim)
    # alive across rounds, so attach/detach races against a LIVE
    # entry, not just create/destroy cycles
    keeper, ksrc, ksink = _stage_pipe("keeper")
    keeper.start()
    threads = [threading.Thread(target=churn, args=(t,))
               for t in range(3)]
    for t in threads:
        t.start()
    try:
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads)
    finally:
        keeper.stop()
    assert not errors, errors
    assert outcomes["frames"] == 3 * rounds * per_round

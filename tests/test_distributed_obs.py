"""Distributed observability (ISSUE-5): cross-device trace propagation,
per-link edge metrics, fleet ``nns-top``.

In-process client+server pipelines over REAL TCP sockets exercise the
full wire path: trace context injection/extraction, the 4-timestamp
clock alignment that nests the server's spans inside the client's
network span, byte-exact ``nns_edge_*`` link counters, the ``/healthz``
probe, multi-endpoint ``nns-top`` with LINK rows and unreachable-
endpoint resilience, and the jax-profiler trace-id correlation marker.
The true two-process variant lives in ``tests/test_crossprocess.py``.
"""

import io
import json
import time
import urllib.request

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.edge.wire import MSG_QUERY, MSG_REPLY, EdgeMessage
from nnstreamer_tpu.elements.basic import AppSink, AppSrc
from nnstreamer_tpu.filters.custom import register_custom_easy
from nnstreamer_tpu.obs import REGISTRY, TRACE_META_KEY, LatencyTracer, hooks
from nnstreamer_tpu.obs.metrics import LinkMetrics, MetricsRegistry
from nnstreamer_tpu.obs.top import fetch_fleet, render_fleet
from nnstreamer_tpu.obs.top import main as top_main
from nnstreamer_tpu.runtime import Pipeline
from nnstreamer_tpu.runtime.registry import make

SHAPE_SPEC = "4:1"
CAPS = ("other/tensors,format=static,num_tensors=1,dimensions=4:1,"
        "types=float32")


@pytest.fixture(autouse=True)
def _clean():
    LinkMetrics.clear_all()
    yield
    hooks.detach()
    LinkMetrics.clear_all()


@pytest.fixture(scope="module", autouse=True)
def _model():
    spec = TensorsSpec.parse(SHAPE_SPEC, "float32")
    register_custom_easy("dobs_x3", lambda xs: [xs[0] * 3.0],
                         in_spec=spec, out_spec=spec)
    yield


def _server(server_id=81):
    srv = Pipeline(name=f"dobs-server-{server_id}")
    qsrc = make("tensor_query_serversrc", el_name="qsrc",
                connect_type="tcp", host="127.0.0.1", port=0,
                id=server_id)
    flt = make("tensor_filter", el_name="srvnet", framework="custom-easy",
               model="dobs_x3")
    qsink = make("tensor_query_serversink", el_name="qsink", id=server_id)
    srv.add(qsrc, flt, qsink).link(qsrc, flt, qsink)
    srv.start()
    return srv, qsrc.port


def _client(port, name="dobs-client", **cli_props):
    spec = TensorsSpec.parse(SHAPE_SPEC, "float32")
    p = Pipeline(name=name)
    src = AppSrc(name="src", spec=spec, max_buffers=64)
    cli = make("tensor_query_client", el_name="qcli", host="127.0.0.1",
               port=port, connect_type="tcp", timeout=30000, caps=CAPS,
               **cli_props)
    sink = AppSink(name="out", max_buffers=64)
    p.add(src, cli, sink).link(src, cli, sink)
    return p, src, cli, sink


def _roundtrip(p, src, sink, n=6):
    outs = []
    with p:
        for i in range(n):
            src.push_buffer(Buffer.of(
                np.full((1, 4), float(i + 1), np.float32), pts=i))
        for _ in range(n):
            b = sink.pull(timeout=30)
            assert b is not None, f"stalled after {len(outs)}"
            outs.append(b)
        src.end_of_stream()
        assert p.wait_eos(timeout=30)
    return outs


# -- trace propagation + clock alignment --------------------------------------


def test_query_trace_crosses_tcp_and_nests():
    """The acceptance shape, in-process: every client record gains a
    remote entry whose offset-mapped server spans nest inside the
    client's network span, which nests inside the client element's
    residency — and local exactness (sum(residency) == e2e) still
    holds."""
    srv, port = _server(81)
    try:
        p, src, cli, sink = _client(port)
        with LatencyTracer(sample_every=1) as tr:
            outs = _roundtrip(p, src, sink, n=6)
        for i, b in enumerate(outs):
            np.testing.assert_array_equal(
                b.tensors[0].np(),
                np.full((1, 4), 3.0 * (i + 1), np.float32))
    finally:
        srv.stop()
    recs = [r for r in tr.records() if r.get("origin") != "remote"]
    assert len(recs) == 6
    for r in recs:
        # local exactness guarantee is untouched by absorption
        assert sum(r["residency_s"].values()) == pytest.approx(
            r["e2e_s"], abs=1e-6)
        assert r.get("remote"), r
        hop = r["remote"][0]
        assert hop["link"] == "qcli"
        # the client element's residency span brackets the network span
        marks = r["marks"]
        cli_in = min(t for t, name, ph in marks
                     if name == "qcli" and ph == "chain-in")
        out_in = min(t for t, name, ph in marks
                     if name == "out" and ph == "chain-in")
        assert cli_in <= hop["t_out"] <= hop["t_in"] <= out_in
        # mapped server window nests inside the network span (the
        # offset_and_delay containment property)
        assert hop["t_out"] <= hop["t2"] <= hop["t3"] <= hop["t_in"]
        assert hop["rtt_s"] >= 0
        # server marks cover the server pipeline and sit in the window
        names = {name for _, name, _ in hop["marks"]}
        assert {"qsrc", "srvnet", "qsink"} <= names
        eps = 5e-4
        for t, _, _ in hop["marks"]:
            assert hop["t_out"] - eps <= t <= hop["t_in"] + eps
    # server-side views were recorded too, tagged remote-origin
    remote_recs = [r for r in tr.records() if r.get("origin") == "remote"]
    assert len(remote_recs) == 6
    # and the traced round-trips fed the per-peer clock
    # (the client element object is gone with the pipeline; the record
    # count above already proves absorption ran)


def test_merged_chrome_trace_one_timeline():
    srv, port = _server(82)
    try:
        p, src, cli, sink = _client(port, name="dobs-ct")
        with LatencyTracer(sample_every=1) as tr:
            _roundtrip(p, src, sink, n=4)
        assert len(cli.peer_clock) > 0  # round-trips fed the PeerClock
    finally:
        srv.stop()
    doc = json.loads(json.dumps(tr.chrome_trace()))
    events = doc["traceEvents"]
    # remote-origin (server-view) records are excluded by default...
    frames = [e for e in events if e["cat"] == "frame"]
    assert len(frames) == 4
    by_tid = {e["tid"]: e for e in frames}
    nets = [e for e in events if e["cat"] == "net"]
    assert len(nets) == 4
    for net in nets:
        frame = by_tid[net["tid"]]
        assert net["ts"] >= frame["ts"] - 1e-3
        assert net["ts"] + net["dur"] <= frame["ts"] + frame["dur"] + 1e-3
        # the server's element spans nest inside THIS network span
        host = net["args"]["host"]
        remote_els = [e for e in events if e["cat"] == "element"
                      and e["tid"] == net["tid"]
                      and e["name"].startswith(f"{host}/")]
        assert {e["name"].split("/", 1)[1] for e in remote_els} \
            >= {"qsrc", "srvnet", "qsink"}
        for e in remote_els:
            assert e["ts"] >= net["ts"] - 1e-3
            assert e["ts"] + e["dur"] <= net["ts"] + net["dur"] + 1e-3
        # the client element span = server residency + network time
        cli_span = [e for e in events if e["cat"] == "element"
                    and e["tid"] == net["tid"] and e["name"] == "qcli"][0]
        assert cli_span["ts"] - 1e-3 <= net["ts"]
        assert net["ts"] + net["dur"] <= \
            cli_span["ts"] + cli_span["dur"] + 1e-3
    # opting in renders the server-view lanes as well
    full = tr.chrome_trace(include_remote_origin=True)
    assert len([e for e in full["traceEvents"]
                if e["cat"] == "frame"]) == 8


def test_trace_false_propagates_nothing():
    srv, port = _server(83)
    try:
        p, src, cli, sink = _client(port, name="dobs-notrace",
                                    trace=False)
        with LatencyTracer(sample_every=1) as tr:
            _roundtrip(p, src, sink, n=3)
    finally:
        srv.stop()
    # client-side records: no remote entries absorbed
    recs = [r for r in tr.records()
            if any(name == "out" for _, name, _ in r["marks"])]
    assert len(recs) == 3
    assert all(not r.get("remote") for r in recs)
    # no propagated context reached the server: its (locally sampled)
    # records are plain, never remote-origin
    assert all(r.get("origin") != "remote" for r in tr.records())


def test_edge_pubsub_oneway_trace():
    """edgesink → edgesrc over TCP: the subscriber's new trace carries
    the publisher's offset-mapped marks as a remote entry."""
    pub = Pipeline(name="dobs-pub")
    spec = TensorsSpec.parse(SHAPE_SPEC, "float32")
    psrc = AppSrc(name="psrc", spec=spec, max_buffers=32)
    esink = make("edgesink", el_name="esink", host="127.0.0.1", port=0,
                 connect_type="tcp", topic="t5")
    pub.add(psrc, esink).link(psrc, esink)
    with LatencyTracer(sample_every=1) as tr:
        pub.start()
        sub = Pipeline(name="dobs-sub")
        esrc = make("edgesrc", el_name="esrc", dest_host="127.0.0.1",
                    dest_port=esink.port, connect_type="tcp", topic="t5",
                    caps=CAPS, num_buffers=3)
        ssink = AppSink(name="ssink", max_buffers=32)
        sub.add(esrc, ssink).link(esrc, ssink)
        sub.start()
        try:
            time.sleep(0.3)  # let the subscription land
            for i in range(3):
                psrc.push_buffer(Buffer.of(
                    np.full((1, 4), float(i), np.float32), pts=i))
            got = [ssink.pull(timeout=10) for _ in range(3)]
            assert all(b is not None for b in got)
            assert sub.wait_eos(timeout=10)
        finally:
            sub.stop()
            pub.stop()
    # subscriber-side records carry the publisher's marks
    sub_recs = [r for r in tr.records()
                if any(name == "ssink" for _, name, _ in r["marks"])]
    assert len(sub_recs) == 3
    for r in sub_recs:
        hop = r["remote"][0]
        assert hop["link"] == "esrc"
        assert {name for _, name, _ in hop["marks"]} >= {"psrc"}
        assert hop["t_in"] <= r["end"]
    # link metrics exist for both directions
    kinds = {row["kind"] for row in REGISTRY.snapshot()["links"]}
    assert {"edge-pub", "edge-sub"} <= kinds


# -- link metrics --------------------------------------------------------------


def test_link_byte_counters_exact():
    """The acceptance bound: exported nns_edge_* byte counters EQUAL
    the ground-truth framed sizes (4-byte length prefix + wire bytes),
    both directions.  Trace off, caps pinned AND the device-channel
    probe off, so every byte on the link is one of the N query/reply
    frames."""
    srv, port = _server(84)
    n = 5
    try:
        p, src, cli, sink = _client(port, name="dobs-bytes", trace=False,
                                    device_channel=False)
        outs = _roundtrip(p, src, sink, n=n)
    finally:
        srv.stop()
    ins = [Buffer.of(np.full((1, 4), float(i + 1), np.float32), pts=i)
           for i in range(n)]
    tx_truth = sum(
        4 + len(EdgeMessage.from_buffer(MSG_QUERY, b, seq=i + 1).pack())
        for i, b in enumerate(ins))
    rx_truth = sum(
        4 + len(EdgeMessage.from_buffer(MSG_REPLY, b, client_id=1,
                                        seq=i + 1).pack())
        for i, b in enumerate(outs))
    rows = {(r["kind"], r["link"]): r
            for r in REGISTRY.snapshot()["links"]}
    cli_row = rows[("query", "qcli")]
    assert cli_row["tx_bytes"] == tx_truth
    assert cli_row["rx_bytes"] == rx_truth
    assert cli_row["tx_msgs"] == n and cli_row["rx_msgs"] == n
    assert cli_row["rtt"]["count"] == n
    assert cli_row["rtt"]["mean_us"] > 0
    assert cli_row["inflight"] == 0 and cli_row["timeouts"] == 0
    # the server side mirrors the link (rx of queries, tx of replies)
    srv_row = rows[("query-server", "qsrc")]
    assert srv_row["rx_bytes"] == tx_truth
    assert srv_row["tx_bytes"] == rx_truth
    # and the flat exposition carries the same numbers (labels render
    # sorted: kind, link, peer)
    expo = REGISTRY.exposition()
    line = [ln for ln in expo.splitlines()
            if ln.startswith('nns_edge_tx_bytes_total{kind="query",'
                             'link="qcli"')][0]
    assert line.endswith(f" {tx_truth}")
    assert "# TYPE nns_edge_rtt_seconds histogram" in expo
    assert "nns_edge_rtt_seconds_bucket" in expo
    assert f'nns_edge_rtt_seconds_count{{kind="query",link="qcli",' \
           f'peer="{cli_row["peer"]}"}} {n}' in expo


def test_link_timeout_counter():
    """A server that never answers surfaces as nns_edge timeouts."""
    from nnstreamer_tpu.edge.transport import TcpServer

    black_hole = TcpServer("127.0.0.1", 0)
    black_hole.start()
    try:
        p, src, cli, sink = _client(black_hole.port, name="dobs-to",
                                    trace=False)
        p.start()
        try:
            src.push_buffer(Buffer.of(np.zeros((1, 4), np.float32)))
            cli.timeout = 100  # shrink AFTER start: fast expiry
            deadline = time.monotonic() + 10
            while cli.timeouts == 0 and time.monotonic() < deadline:
                time.sleep(0.05)
        finally:
            p.stop()
        row = [r for r in REGISTRY.snapshot()["links"]
               if r["link"] == "qcli" and r["kind"] == "query"][0]
        assert row["timeouts"] >= 1
    finally:
        black_hole.stop()


# -- /healthz ------------------------------------------------------------------


def test_healthz_endpoint():
    reg = MetricsRegistry()
    p = Pipeline(name="dobs-hz")
    reg.register_pipeline(p)
    srv = reg.serve(port=0)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz", timeout=5) as r:
            assert r.status == 200
            doc = json.loads(r.read().decode())
        assert doc["status"] == "ok"
        assert doc["pipelines"] == 1
        assert "pools" in doc and "links" in doc and "time" in doc
        assert doc["host"]
    finally:
        srv.close()


# -- fleet nns-top -------------------------------------------------------------


def _registry_with_pipeline(name, collect_links=False):
    reg = MetricsRegistry(collect_links=collect_links)
    spec = TensorsSpec.parse(SHAPE_SPEC, "float32")
    p = Pipeline(name=name)
    src = AppSrc(name="src", spec=spec)
    sink = AppSink(name="out")
    p.add(src, sink).link(src, sink)
    reg.register_pipeline(p)
    return reg, p


def test_nns_top_fleet_two_endpoints():
    """--connect twice: one table, sectioned per endpoint, both hosts'
    PIPELINE rows visible; LINK rows render from the links table."""
    LinkMetrics.get("qcli", "10.0.0.7:9000", kind="query").on_tx(128)
    rega, pa = _registry_with_pipeline("fleet-a", collect_links=True)
    regb, pb = _registry_with_pipeline("fleet-b")
    sa, sb = rega.serve(port=0), regb.serve(port=0)
    try:
        buf = io.StringIO()
        rc = top_main(["--once", "--interval", "0.05",
                       "--connect", f"127.0.0.1:{sa.port}",
                       "--connect", f"127.0.0.1:{sb.port}"], out=buf)
        text = buf.getvalue()
        assert rc == 0
        assert f"endpoint 127.0.0.1:{sa.port}" in text
        assert f"endpoint 127.0.0.1:{sb.port}" in text
        assert "pipeline fleet-a" in text
        assert "pipeline fleet-b" in text
        assert "LINK" in text and "10.0.0.7:9000" in text
        assert "RTT µs" in text and "RECON" in text
        # comma-separated form is equivalent
        buf2 = io.StringIO()
        rc = top_main(["--once", "--interval", "0.05", "--connect",
                       f"127.0.0.1:{sa.port},127.0.0.1:{sb.port}"],
                      out=buf2)
        assert rc == 0
        assert "pipeline fleet-a" in buf2.getvalue()
        assert "pipeline fleet-b" in buf2.getvalue()
    finally:
        sa.close()
        sb.close()


def test_nns_top_partial_outage_keeps_rendering():
    """One live endpoint + one dead: --once still renders the live one
    (rc 0) and marks the dead one; a fully dead fleet is rc 1."""
    reg, p = _registry_with_pipeline("fleet-live")
    srv = reg.serve(port=0)
    try:
        buf = io.StringIO()
        rc = top_main(["--once", "--interval", "0.05",
                       "--connect", f"127.0.0.1:{srv.port}",
                       "--connect", "127.0.0.1:1"], out=buf)
        text = buf.getvalue()
        assert rc == 0
        assert "pipeline fleet-live" in text
        assert "unreachable (retrying)" in text
    finally:
        srv.close()
    buf = io.StringIO()
    rc = top_main(["--once", "--interval", "0.05",
                   "--connect", "127.0.0.1:1"], out=buf)
    assert rc == 1


def test_fetch_fleet_and_render_survive_dead_endpoint():
    """The live-mode resilience primitive: a scrape failure becomes a
    rendered 'unreachable (retrying)' line, never an exception — so a
    restarting server can't kill the dashboard loop."""
    samples = fetch_fleet(["127.0.0.1:1"])
    assert samples[0]["snap"] is None
    assert samples[0]["error"]
    text = render_fleet(samples, {}, show_host=True)
    assert "unreachable (retrying)" in text
    # recovery: same endpoint answering again renders normally
    reg, p = _registry_with_pipeline("fleet-back")
    srv = reg.serve(port=0)
    try:
        again = fetch_fleet([f"127.0.0.1:{srv.port}"])
        assert again[0]["snap"] is not None
        assert "pipeline fleet-back" in render_fleet(again, {}, True)
    finally:
        srv.close()


def test_fetch_fleet_captures_non_oserror_failures(monkeypatch):
    """A process dying mid-response raises HTTPException/ValueError,
    not OSError — the fleet loop must survive those identically."""
    from http.client import IncompleteRead

    from nnstreamer_tpu.obs import top as top_mod

    for exc in (IncompleteRead(b""), ValueError("truncated json")):
        def boom(ep, _e=exc):
            raise _e
        monkeypatch.setattr(top_mod, "fetch_snapshot", boom)
        samples = top_mod.fetch_fleet(["127.0.0.1:9"])
        assert samples[0]["snap"] is None and samples[0]["error"]
        assert "unreachable (retrying)" in \
            render_fleet(samples, {}, show_host=True)


def test_async_ntp_epoch_fn_never_blocks():
    """The element-facing epoch callable must stay hot-path safe even
    with unreachable NTP servers: first call returns the local clock
    immediately; the SNTP walk happens on the refresh thread."""
    from nnstreamer_tpu.edge.ntputil import async_ntp_epoch_fn

    fn = async_ntp_epoch_fn([("127.0.0.1", 1)])
    try:
        t0 = time.monotonic()
        us = fn()
        assert time.monotonic() - t0 < 0.25  # no 2s SNTP timeout inline
        assert abs(us - time.time() * 1e6) < 5e6
    finally:
        fn.stop()


def test_clock_cross_check_warns_on_persistent_disagreement(caplog):
    """ntp-servers= is a REAL cross-check: a server epoch that
    persistently disagrees with the in-band half-RTT placement logs a
    skew warning; an agreeing one resets the streak."""
    import logging

    cli = make("tensor_query_client", el_name="xchk",
               ntp_servers="198.51.100.9")
    cli._epoch_fn = lambda: 1_000_000_000  # stub: no network
    est = (0.0, 0.010)  # delay 10ms → expected lag_wall ≈ 5ms
    agree = {"epoch3_us": 1_000_000_000 - 5_000}
    skewed = {"epoch3_us": 1_000_000_000 - 80_000}  # 80ms lag: way off
    with caplog.at_level(logging.WARNING, logger="nnstreamer_tpu"):
        for _ in range(4):
            cli._clock_cross_check(skewed, est)
        assert cli._clock_disagree == 4
        cli._clock_cross_check(agree, est)
        assert cli._clock_disagree == 0  # one good sample resets
        assert not caplog.records
        for _ in range(5):
            cli._clock_cross_check(skewed, est)
    assert any("disagree" in r.getMessage() for r in caplog.records)
    assert cli._clock_disagree == 0  # warned once, streak reset


def test_inflight_gauge_counts_only_unanswered():
    """One definition everywhere: the gauge counts entries awaiting a
    reply — an answered-but-not-yet-popped entry is excluded whether
    the writer was chain() or the flush path."""
    cli = make("tensor_query_client", el_name="ifl")
    cli._metrics = LinkMetrics.get("ifl", "x:1", kind="query")
    with cli._iflock:
        cli._inflight[1] = [object(), None, 0.0, None, 0.0]
        cli._inflight[2] = [object(), object(), 0.0, None, 0.0]  # answered
        cli._inflight[3] = [None, None, 0.0, None, 0.0]          # tombstone
        cli._update_inflight_locked()
    assert cli._metrics.snapshot()["inflight"] == 1


# -- device-trace correlation marker -------------------------------------------


def test_frame_annotation_marker(monkeypatch):
    from nnstreamer_tpu.utils import profile

    seen = []

    class FakeAnnotation:
        def __init__(self, name):
            seen.append(name)

        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

    import jax

    monkeypatch.setattr(jax.profiler, "TraceAnnotation", FakeAnnotation)
    # inactive profiler: no-op regardless of ids
    with profile.frame_annotation(["aa-1"]):
        pass
    assert seen == []
    profile._active.set()
    try:
        with profile.frame_annotation([]):
            pass
        assert seen == []  # no sampled frames: still no annotation
        with profile.frame_annotation(["aa-1", "bb-2"]):
            pass
        assert seen == ["nns:frames:aa-1,bb-2"]
    finally:
        profile._active.clear()


def test_dispatch_carries_trace_id_to_annotation(monkeypatch):
    """End to end: a traced frame through tensor_filter under an
    active profiler wraps the invoke in nns:frames:<id>."""
    from nnstreamer_tpu.utils import profile

    seen = []

    class FakeAnnotation:
        def __init__(self, name):
            seen.append(name)

        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

    import jax

    monkeypatch.setattr(jax.profiler, "TraceAnnotation", FakeAnnotation)
    spec = TensorsSpec.parse(SHAPE_SPEC, "float32")
    p = Pipeline(name="dobs-ann")
    src = AppSrc(name="src", spec=spec, max_buffers=8)
    flt = make("tensor_filter", el_name="net", framework="custom-easy",
               model="dobs_x3")
    sink = AppSink(name="out", max_buffers=8)
    p.add(src, flt, sink).link(src, flt, sink)
    profile._active.set()
    try:
        with LatencyTracer(sample_every=1) as tr:
            with p:
                src.push_buffer(Buffer.of(
                    np.ones((1, 4), np.float32), pts=0))
                src.end_of_stream()
                assert p.wait_eos(timeout=10)
        rid = tr.records()[0]["id"]
    finally:
        profile._active.clear()
    # per-element annotate() spans record too; the frame marker is the
    # one carrying the trace id
    assert f"nns:frames:{rid}" in seen

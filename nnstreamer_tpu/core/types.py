"""Tensor type system for the TPU-native stream framework.

Behavioral parity with the reference type system
(/root/reference/gst/nnstreamer/include/tensor_typedef.h:33-153):
11 element dtypes, rank limit 16, up to 256 tensors per frame, three stream
formats (static / flexible / sparse), NHWC/NCHW layout tags.  Redesigned for
JAX: every dtype maps onto a canonical ``jnp.dtype`` and the framework adds
``bfloat16`` as a TPU-first extension (the MXU's native compute type), which
the reference cannot express.
"""

from __future__ import annotations

import enum
from typing import Optional

import numpy as np

# Limits — parity with tensor_typedef.h:33-57.
TENSOR_RANK_LIMIT = 16
TENSOR_COUNT_LIMIT = 256
# The reference packs at most 16 tensors as native GstMemory chunks and the
# rest into an "extra" region (tensor_typedef.h:44-57). We have no GstBuffer,
# so the only observable limit is the 256 total.
TENSOR_MEMORY_MAX = 16

MIMETYPE_TENSOR = "other/tensor"
MIMETYPE_TENSORS = "other/tensors"


class DType(enum.Enum):
    """Element types of a tensor stream (tensor_typedef.h:138-153).

    Values keep the reference's enum ordering so serialized meta headers are
    cross-readable; BFLOAT16 is appended past the reference's range.
    """

    INT32 = 0
    UINT32 = 1
    INT16 = 2
    UINT16 = 3
    INT8 = 4
    UINT8 = 5
    FLOAT64 = 6
    FLOAT32 = 7
    INT64 = 8
    UINT64 = 9
    FLOAT16 = 10
    # TPU-native extension (not in the reference enum).
    BFLOAT16 = 32

    @property
    def np_dtype(self) -> np.dtype:
        return _NP_DTYPES[self]

    @property
    def size(self) -> int:
        """Bytes per element."""
        return _NP_DTYPES[self].itemsize

    @classmethod
    def from_string(cls, s: str) -> "DType":
        try:
            return _STR_TO_DTYPE[s.strip().lower()]
        except KeyError:
            raise ValueError(f"unknown tensor dtype string: {s!r}") from None

    @classmethod
    def from_np(cls, dt) -> "DType":
        dt = np.dtype(dt) if not _is_bfloat16(dt) else dt
        for k, v in _NP_DTYPES.items():
            if v == dt:
                return k
        raise ValueError(f"unsupported numpy dtype: {dt!r}")

    def __str__(self) -> str:
        return _DTYPE_TO_STR[self]


def _make_bfloat16():
    # ml_dtypes ships with jax; if it is ever absent, fail loudly rather
    # than aliasing bfloat16 to another dtype (which would corrupt wire
    # headers that claim 2-byte elements).
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


def _is_bfloat16(dt) -> bool:
    return getattr(dt, "name", None) == "bfloat16" or dt == "bfloat16"


_NP_DTYPES = {
    DType.INT32: np.dtype(np.int32),
    DType.UINT32: np.dtype(np.uint32),
    DType.INT16: np.dtype(np.int16),
    DType.UINT16: np.dtype(np.uint16),
    DType.INT8: np.dtype(np.int8),
    DType.UINT8: np.dtype(np.uint8),
    DType.FLOAT64: np.dtype(np.float64),
    DType.FLOAT32: np.dtype(np.float32),
    DType.INT64: np.dtype(np.int64),
    DType.UINT64: np.dtype(np.uint64),
    DType.FLOAT16: np.dtype(np.float16),
    DType.BFLOAT16: _make_bfloat16(),
}

_DTYPE_TO_STR = {
    DType.INT32: "int32",
    DType.UINT32: "uint32",
    DType.INT16: "int16",
    DType.UINT16: "uint16",
    DType.INT8: "int8",
    DType.UINT8: "uint8",
    DType.FLOAT64: "float64",
    DType.FLOAT32: "float32",
    DType.INT64: "int64",
    DType.UINT64: "uint64",
    DType.FLOAT16: "float16",
    DType.BFLOAT16: "bfloat16",
}
_STR_TO_DTYPE = {v: k for k, v in _DTYPE_TO_STR.items()}


class TensorFormat(enum.Enum):
    """Data format of a tensor stream (tensor_typedef.h:158-166)."""

    STATIC = 0
    FLEXIBLE = 1
    SPARSE = 2

    @classmethod
    def from_string(cls, s: str) -> "TensorFormat":
        try:
            return cls[s.strip().upper()]
        except KeyError:
            raise ValueError(f"unknown tensor format: {s!r}") from None

    def __str__(self) -> str:
        return self.name.lower()


class TensorLayout(enum.Enum):
    """Memory layout hint (tensor_typedef.h:188-196)."""

    ANY = 0
    NHWC = 1
    NCHW = 2
    NONE = 3

    def __str__(self) -> str:
        return self.name.lower()


class MediaType(enum.Enum):
    """Source media type carried in flexible-tensor meta
    (tensor_typedef.h:171-183)."""

    OCTET = -1
    TENSOR = 0
    VIDEO = 1
    AUDIO = 2
    TEXT = 3

    @classmethod
    def from_mime(cls, mime: str) -> "MediaType":
        return _MIME_TO_MEDIA.get(mime, cls.OCTET)


_MIME_TO_MEDIA = {
    MIMETYPE_TENSOR: MediaType.TENSOR,
    MIMETYPE_TENSORS: MediaType.TENSOR,
    "video/x-raw": MediaType.VIDEO,
    "audio/x-raw": MediaType.AUDIO,
    "text/x-raw": MediaType.TEXT,
    "application/octet-stream": MediaType.OCTET,
}


def dtype_range(dtype: DType) -> Optional[tuple]:
    """(min, max) representable values for integer dtypes, None for floats.

    Used by transform clamp/typecast saturation paths (parity with
    tensor_data.c typed scalar math).
    """
    np_dt = dtype.np_dtype
    if np_dt.kind in "iu":
        info = np.iinfo(np_dt)
        return (info.min, info.max)
    return None

"""Device channel: the same-pod ICI fast path for edge transports.

The TCP edge layer pays the full host round-trip per frame: the sender
drains every device tensor to host (``Tensor.tobytes``), the bytes ride
a socket, and the receiver re-uploads them — a d2h+h2d pair *per hop*
even when both pipeline endpoints run against the same accelerator pod.
This module removes that pair: when two endpoints prove (by handshake)
that they resolve into one device mesh, frames stay **in HBM** and only
control metadata crosses the socket.

How it composes with the rest of the edge stack:

- :func:`fingerprint` names this process's device world — the jax
  runtime instance plus the platform/device-count the ``Placement``
  layer (parallel/placement.py) would resolve a mesh over.  Two
  endpoints with equal fingerprints share one jax runtime, hence one
  pod: a ``jax.Array`` handle deposited by one is directly consumable
  by the other, and a cross-*device* handoff inside that pod is a
  ``device_put`` (device-to-device over ICI) or, for sharded streams, a
  collective from :mod:`nnstreamer_tpu.parallel.collectives`
  (``all_gather_merge`` for fan-in, ``ring_shift`` for neighbor
  streaming) — never a host bounce.
- The handshake rides the wire as ``MSG_DEVCH_REQ``/``MSG_DEVCH_RES``
  (edge/wire.py): the initiator sends its fingerprint, the peer replies
  ``ok`` only on an exact match and marks the connection
  device-channel-capable.  Anything else — a different process, a
  different pod, an old binary that drops the unknown message — leaves
  the connection in plain TCP mode: the fallback is the absence of the
  fast path, so it is transparent by construction.
- On a capable connection the sender deposits the frame's device
  arrays here (:func:`deposit_buffer`) and sends a control-only wire
  frame carrying an ``EXT_DEVCH`` descriptor (slot id + fingerprint +
  byte count) instead of payloads; the receiver redeems the slot
  (:func:`take_buffer`).  The transfer ledger (obs/transfer.py) sees no
  crossing because none happens — which is exactly the
  ``crossings_per_frame`` → 0 this PR is gated on.

Slots are bounded: a dropped control frame (chaos, disconnect) leaks
its slot until FIFO eviction reclaims it, counted in :func:`stats`.
"""

from __future__ import annotations

import itertools
import threading
import uuid
from collections import OrderedDict
from typing import Any, Dict, List, Optional

from ..core import Buffer, Tensor
from ..utils.log import logw

#: handshake reply payload on a fingerprint match
DEVCH_OK = "ok"

#: per-CHANNEL bound on parked frames awaiting redemption; beyond it
#: that channel's OLDEST slot evicts (its control frame was lost or
#: its receiver is stalled — the receiver surfaces a timeout/drop,
#: like a lost payload frame on plain TCP).  The bound is per sending
#: connection (the ``chan`` tag), so one stalled subscriber's backlog
#: can never evict a healthy link's in-flight frames.
MAX_SLOTS = 512

_PROC_TAG = uuid.uuid4().hex[:12]
_lock = threading.Lock()
#: chan tag → (slot id → parked Buffer); slots are globally unique
#: strings, the chan grouping only scopes the bound/eviction
_slots: "Dict[Any, OrderedDict[str, Buffer]]" = {}
_slot_ids = itertools.count(1)
_fp_cache: Optional[str] = None

#: counters for tests/bench/nns-top (guarded by _lock):
#: deposits/takes/misses/evicted are frame counts, bytes_resident is
#: the payload volume that stayed in HBM instead of crossing twice
_stats = {"deposits": 0, "takes": 0, "misses": 0, "evicted": 0,
          "bytes_resident": 0}


def fingerprint() -> str:
    """This process's device-world identity: process tag + platform +
    device count.  Equal fingerprints ⇔ the two endpoints hold handles
    into the SAME jax runtime (same process, same pod) — the only
    condition under which a deposited ``jax.Array`` is consumable on
    the other side without serialization.  Computed lazily so importing
    the edge layer never initializes jax."""
    global _fp_cache
    if _fp_cache is None:
        try:
            import jax

            devs = jax.devices()
            plat = devs[0].platform if devs else "none"
            _fp_cache = f"{_PROC_TAG}/{plat}x{len(devs)}"
        except Exception:  # noqa: BLE001 - no jax/devices: no fast path
            _fp_cache = f"{_PROC_TAG}/none"
    return _fp_cache


def handshake_ok(peer_fp: str) -> bool:
    """Peer's fingerprint names the same device world as ours."""
    return bool(peer_fp) and peer_fp == fingerprint()


def eligible(buf: Buffer) -> bool:
    """A frame rides the device channel only when it is FULLY
    device-resident: a host/mixed frame would need its host tensors
    serialized anyway, at which point plain TCP is the simpler path."""
    return bool(buf.tensors) and buf.residency == "device"


def deposit_buffer(buf: Buffer, chan: Any = "") -> Dict[str, Any]:
    """Park a device-resident frame and return the wire descriptor
    (``EXT_DEVCH``): fingerprint + slot id + byte count.  The arrays
    never leave HBM — the descriptor is the only thing that crosses
    the socket.  ``chan`` scopes the slot bound to the sending
    connection so links evict independently."""
    slot = f"{_PROC_TAG}-{next(_slot_ids)}"
    nbytes = buf.nbytes
    with _lock:
        ch = _slots.get(chan)
        if ch is None:
            ch = _slots[chan] = OrderedDict()
        ch[slot] = buf
        _stats["deposits"] += 1
        _stats["bytes_resident"] += nbytes
        while len(ch) > MAX_SLOTS:
            ch.popitem(last=False)
            _stats["evicted"] += 1
    return {"fp": fingerprint(), "slot": slot, "nbytes": nbytes}


def take_buffer(desc: Dict[str, Any],
                device: Any = None) -> Optional[Buffer]:
    """Redeem a descriptor: pop the parked frame (tensors by reference,
    meta shallow-copied so the consumer can stamp routing keys without
    mutating the producer's view).  Returns None — logged once per
    reason — when the fingerprint is foreign (a sender skipped the
    handshake) or the slot was evicted.

    ``device`` optionally re-homes the tensors: on a real pod the
    ``device_put`` of an HBM-resident array to a sibling chip is a
    device-to-device ICI copy, the submesh-handoff story (two pipeline
    stages on disjoint chips of one pod); sharded fan-in instead goes
    through ``parallel.collectives.all_gather_merge``."""
    fp = str(desc.get("fp", ""))
    if fp != fingerprint():
        with _lock:
            _stats["misses"] += 1
        logw("devicechannel: frame from foreign device world %s "
             "(ours %s) — sender bypassed the handshake; frame dropped",
             fp, fingerprint())
        return None
    slot = str(desc.get("slot", ""))
    with _lock:
        buf = None
        for tag, ch in list(_slots.items()):
            buf = ch.pop(slot, None)
            if not ch:
                del _slots[tag]  # no empty-channel creep
            if buf is not None:
                break
        if buf is None:
            _stats["misses"] += 1
        else:
            _stats["takes"] += 1
    if buf is None:
        logw("devicechannel: slot %s already redeemed or evicted",
             desc.get("slot"))
        return None
    out = Buffer(tensors=list(buf.tensors), pts=buf.pts,
                 duration=buf.duration, offset=buf.offset,
                 format=buf.format, meta=dict(buf.meta))
    if device is not None:
        import jax

        out = Buffer(
            tensors=[Tensor(jax.device_put(t.jax(), device), t.spec)
                     for t in out.tensors],
            pts=out.pts, duration=out.duration, offset=out.offset,
            format=out.format, meta=out.meta)
    return out


def stage_handoff(buf: Buffer, device: Any,
                  chan: Any = "stage") -> Buffer:
    """Same-host cross-subset handoff: one pipeline, two stages on
    disjoint device subsets of one pod.  The frame goes through the
    channel's slot semantics — deposit, immediate redeem re-homed onto
    ``device`` (a device-to-device ICI copy on a real pod, never a host
    bounce) — and leaves one byte-exact ``d2d``/``handoff`` row on the
    transfer ledger.  Residency never flips to host, so the
    ``crossings_per_frame == 0.0`` invariant extends across the stage
    boundary by construction.  Returns the original frame untouched
    when it is not fully device-resident (host tensors upload through
    the normal ``h2d`` path) or the slot was evicted under pressure."""
    if not eligible(buf):
        return buf
    import time as _time

    from ..obs import transfer as _xfer

    desc = deposit_buffer(buf, chan=chan)
    t0 = _time.perf_counter()
    out = take_buffer(desc, device=device)
    if out is None:  # evicted under pressure: keep the original frame
        return buf
    _xfer.record("d2d", "handoff", desc["nbytes"],
                 _time.perf_counter() - t0)
    return out


def release_chan(chan: Any) -> None:
    """Drop a sending connection's parked slots (called at connection
    close): frames still awaiting redemption on a dead link can never
    be taken — holding them would pin their HBM for the channel bound's
    lifetime."""
    with _lock:
        ch = _slots.pop(chan, None)
        if ch:
            _stats["evicted"] += len(ch)


def stats() -> Dict[str, int]:
    with _lock:
        return dict(_stats,
                    parked=sum(len(ch) for ch in _slots.values()))


def reset() -> None:
    """Tests only: drop parked slots and zero the counters."""
    with _lock:
        _slots.clear()
        for k in _stats:
            _stats[k] = 0
